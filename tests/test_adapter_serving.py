"""Many-model serving: per-slot LoRA-class adapters on one paged engine
(serving/adapters.py).

The exactness contract is the tentpole gate: in a MIXED-adapter batch
(ids interleaved, base id 0 included) every slot's token stream is
bitwise identical to a solo ``generate_from_params(adapters=...)`` run
of its adapter — greedy AND sampled, for any admission order, single-
chip and mp in {2, 4}. Plus:

  * the two-executable steady state holds WITH adapters on
    (``paged_traces == 2``), and adapter hot-load / evict / in-place
    swap are content-only rewrites — ZERO additional traces;
  * adapter ops never flush the shared-base prefix cache (base traffic
    keys prefix pages by tokens alone; adapted requests' keys carry
    their adapter id + content version, since the out/up/down deltas
    feed the residual stream later layers' KV is computed from), while
    a base ``swap_params`` keeps its full flush — both regression-gated;
  * typed ``UnknownAdapterError`` at construction and submit; requests
    bound to a NON-RESIDENT adapter wait at admission (strict in-order)
    until a load, and mutating an adapter bound to a RUNNING slot is
    refused;
  * WFQ fairness lanes by ADAPTER on an adapter engine
    (``Scheduler(lane_key=)``), and ``FLAGS_serving_tenant_adapters``
    maps tenants to default adapters;
  * kill-and-resume carries the resident adapter set and per-slot
    bindings bitwise; the supervisor's fleet-level adapter ops survive
    replica death and rolling restarts;
  * residency/delta-bytes/token-share land in the metrics ledger and
    the ``adapters:`` serving_summary segment.
"""
import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu import profiler, serving
from paddle_tpu.models.generation import generate_from_params
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import init_gpt_params
from paddle_tpu.serving import metrics as smetrics
from paddle_tpu.serving.adapters import (
    AdapterRegistry, AdapterSpec, UnknownAdapterError,
)
from paddle_tpu.serving.slo import resolve_tenant_adapters
from paddle_tpu.utils import fault_injection as fi

CFG = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=128, dropout=0.0, use_flash=False,
                compute_dtype="float32", remat=False)
_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = init_gpt_params(CFG, jax.random.key(0))
    return _PARAMS


def _engine(**kw):
    kw.setdefault("num_slots", 3)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("adapter_slots", 3)
    kw.setdefault("adapter_rank", 4)
    return serving.Engine(params=_params(), config=CFG, **kw)


def _delta(seed, rank=4, targets=("out_w", "up_w", "down_w")):
    """A deterministic low-rank delta tree (A [L,K,r], B [L,r,F])."""
    rng = np.random.default_rng(seed)
    H, I = CFG.hidden_size, 4 * CFG.hidden_size
    dims = {"out_w": (H, H), "up_w": (H, I), "down_w": (I, H)}
    return {t: (rng.standard_normal(
                    (CFG.num_layers, dims[t][0], rank)).astype(np.float32)
                * 0.05,
                rng.standard_normal(
                    (CFG.num_layers, rank, dims[t][1])).astype(np.float32)
                * 0.05)
            for t in targets}


def _load_std(eng):
    """Load the standard 2-adapter palette; returns the engine."""
    eng.load_adapter(1, _delta(1), alpha=8.0)
    eng.load_adapter(2, _delta(2), alpha=8.0)
    return eng


def _ref_tokens(prompt, max_new, adapters=None, **kw):
    out = np.asarray(generate_from_params(
        _params(), np.asarray(prompt)[None], CFG, max_new_tokens=max_new,
        adapters=adapters, **kw)._data)
    return out[0, len(prompt):].tolist()


def _check_bitwise(eng, reqs, results, **ref_kw):
    """Every request's stream must equal its adapter's SOLO reference."""
    slabs = eng.adapters.device_slabs()
    for r in reqs:
        aid = r.adapter or 0
        kw = dict(ref_kw)
        if r.do_sample:
            kw.update(do_sample=True, temperature=r.temperature,
                      top_p=r.top_p, seed=r.seed)
        ref = _ref_tokens(r.prompt, r.max_new_tokens,
                          adapters=(aid, slabs), **kw)
        got = results[r.request_id].tokens
        assert got == ref[:len(got)] and got, \
            f"adapter {aid} request {r.request_id}: {got} != {ref}"


_SHAPES = ((3, 4), (5, 6), (9, 4), (13, 6), (21, 5), (4, 4))


def _mixed_requests(order, rng, sampled=False):
    reqs = []
    for i, aid in enumerate(order):
        plen, mnt = _SHAPES[i % len(_SHAPES)]
        kw = {}
        if sampled:
            kw = dict(do_sample=True, temperature=0.9, top_p=0.9,
                      seed=100 + i)
        reqs.append(serving.Request(rng.integers(0, CFG.vocab_size, plen),
                                    max_new_tokens=mnt, adapter=aid, **kw))
    return reqs


# ---------------------------------------------------------------------------
# tentpole: mixed-adapter bitwise exactness


@pytest.mark.parametrize("sampled", [False, True])
def test_mixed_adapter_batch_bitwise_two_orders(sampled):
    """A batch interleaving base + two adapters matches each adapter's
    SOLO reference bitwise — greedy and sampled, two admission orders."""
    for order in ((0, 1, 2, 1, 0, 2), (2, 0, 1, 0, 2, 1)):
        eng = _load_std(_engine())
        reqs = _mixed_requests(order, np.random.default_rng(7),
                               sampled=sampled)
        results = eng.run(reqs)
        _check_bitwise(eng, reqs, results)


def test_batch_composition_invariance():
    """The same request decodes identically whether its batch neighbors
    run the base, its own adapter, or a different one — the row-
    independence guarantee of the where-composed delta epilogue."""
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, CFG.vocab_size, 7)
    outs = []
    for neighbors in ((0, 0), (1, 2), (2, 2)):
        eng = _load_std(_engine())
        probe = serving.Request(prompt, max_new_tokens=6, adapter=1)
        reqs = [probe] + [
            serving.Request(rng.integers(0, CFG.vocab_size, 5),
                            max_new_tokens=6, adapter=a) for a in neighbors]
        outs.append(eng.run(reqs)[probe.request_id].tokens)
    assert outs[0] == outs[1] == outs[2], outs


# ---------------------------------------------------------------------------
# zero-retrace gates


def test_two_executables_with_adapters_and_zero_retrace_ops():
    """paged_traces freezes at 2 with adapters on, and hot load / evict /
    swap add ZERO traces — adapter ids are traced operands, adapter ops
    content-only rewrites. (num_slots=6 is unique in the suite:
    executables are shared ACROSS engines per shape, so only fresh
    shapes show warmup traces.)"""
    profiler.reset_serving_counters()
    eng = _load_std(_engine(num_slots=6))
    rng = np.random.default_rng(11)
    eng.run(_mixed_requests((0, 1, 2, 1), rng))
    assert smetrics.serving_counters()["paged_traces"] == 2
    # hot ops while warm: load a third adapter, swap one, evict another
    eng.load_adapter(3, _delta(3), alpha=4.0)
    eng.swap_adapter(1, _delta(41), alpha=8.0)
    eng.evict_adapter(2)
    eng.load_adapter(2, _delta(42), alpha=8.0)
    results = eng.run(_mixed_requests((3, 1, 2, 0, 3), rng))
    assert results
    c = smetrics.serving_counters()
    assert c["paged_traces"] == 2, \
        f"adapter ops retraced: paged_traces={c['paged_traces']}"
    assert c["adapter_loads"] == 4 and c["adapter_evicts"] == 1 \
        and c["adapter_swaps"] == 1
    # the post-op streams serve the NEW content, still bitwise
    more = _mixed_requests((1, 3), rng)
    _check_bitwise(eng, more, eng.run(more))


def test_mixed_adapter_run_still_bitwise_after_swap():
    """swap_adapter changes the bits a NEW request decodes under;
    versions stamp which content each result saw."""
    eng = _load_std(_engine())
    prompt = np.arange(2, 9)
    r1 = serving.Request(prompt, max_new_tokens=6, adapter=1)
    before = eng.run([r1])[r1.request_id]
    v2 = eng.swap_adapter(1, _delta(99), alpha=8.0)
    r2 = serving.Request(prompt, max_new_tokens=6, adapter=1)
    after = eng.run([r2])[r2.request_id]
    slabs = eng.adapters.device_slabs()
    assert after.tokens == _ref_tokens(prompt, 6, adapters=(1, slabs))[
        :len(after.tokens)]
    assert before.adapter_version != after.adapter_version
    assert after.adapter_version == v2
    assert before.adapter == after.adapter == 1


# ---------------------------------------------------------------------------
# prefix-cache invalidation scoping (satellite 1)


def test_adapter_ops_preserve_prefix_cache_base_swap_flushes():
    """Adapter load/evict/swap must NOT flush shared-base prefix pages —
    base traffic keys pages by tokens alone, adapted requests' keys are
    salted with (adapter id, content version) so every hit is content-
    exact — while a base-weight swap_params keeps the full flush."""
    eng = _load_std(_engine())
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG.vocab_size, 17)   # > 2 pages: cacheable
    r = serving.Request(prompt, max_new_tokens=4, adapter=0)
    eng.run([r])
    keys_before = set(eng.pool._cache)
    assert keys_before, "run left no prefix-cache entries; gate is vacuous"
    eng.load_adapter(3, _delta(3))
    eng.swap_adapter(1, _delta(31), alpha=8.0)
    eng.evict_adapter(3)
    assert set(eng.pool._cache) == keys_before, \
        "an adapter op flushed shared-base prefix pages"
    # the preserved BASE pages are reused by later base traffic, exactly
    profiler.reset_serving_counters()
    rb = serving.Request(prompt, max_new_tokens=4, adapter=0)
    _check_bitwise(eng, [rb], eng.run([rb]))
    assert smetrics.serving_counters()["prefix_hits"] >= 1, \
        "base prefix reuse never fired after adapter ops"
    # an ADAPTED request must NOT hit base pages (its prompt KV depends
    # on its delta bits through the residual stream) — and stays exact
    profiler.reset_serving_counters()
    r1 = serving.Request(prompt, max_new_tokens=4, adapter=1)
    _check_bitwise(eng, [r1], eng.run([r1]))
    assert smetrics.serving_counters()["prefix_hits"] == 0, \
        "adapter request consumed base-keyed prefix pages"
    # ... but DOES hit its own salted entries on a repeat, exactly
    profiler.reset_serving_counters()
    r1b = serving.Request(prompt, max_new_tokens=4, adapter=1)
    _check_bitwise(eng, [r1b], eng.run([r1b]))
    assert smetrics.serving_counters()["prefix_hits"] >= 1, \
        "same-adapter prefix reuse never fired"
    # a swap bumps the content version: the stale entries are simply
    # unreachable (no flush), and the post-swap stream is exact
    cached = len(eng.pool._cache)
    eng.swap_adapter(1, _delta(77), alpha=8.0)
    assert len(eng.pool._cache) == cached, "swap_adapter flushed the cache"
    profiler.reset_serving_counters()
    r1c = serving.Request(prompt, max_new_tokens=4, adapter=1)
    _check_bitwise(eng, [r1c], eng.run([r1c]))
    assert smetrics.serving_counters()["prefix_hits"] == 0, \
        "post-swap request hit a pre-swap (stale-content) prefix entry"
    # the full flush is scoped to BASE-weight swaps: still there
    eng.swap_params(_params())
    assert not eng.pool._cache, \
        "swap_params no longer flushes the prefix cache"


# ---------------------------------------------------------------------------
# typed errors, residency-blocking admission, in-use protection


def test_unknown_adapter_typed_errors():
    eng = _engine(adapter_slots=2)
    # out of capacity at submit; error names the id
    with pytest.raises(UnknownAdapterError) as ei:
        eng.submit(serving.Request([1, 2, 3], max_new_tokens=2, adapter=7))
    assert ei.value.adapter_id == 7
    # negative id fails Request validation itself
    with pytest.raises(UnknownAdapterError):
        serving.Request([1, 2, 3], adapter=-1)
    # an adapter-less engine refuses adapter traffic, typed
    plain = serving.Engine(params=_params(), config=CFG, num_slots=2,
                           max_seq_len=96, page_size=8, prefill_chunk=8,
                           kv_layout="paged")
    with pytest.raises(UnknownAdapterError):
        plain.submit(serving.Request([1, 2, 3], max_new_tokens=2, adapter=1))
    # tenant mapping outside capacity is a construction-time error
    with pytest.raises(UnknownAdapterError):
        _engine(adapter_slots=2, tenant_adapters={"acme": 9})


def test_construction_gates():
    with pytest.raises(ValueError, match="paged"):
        _engine(kv_layout="pooled")
    with pytest.raises(ValueError, match="speculative"):
        _engine(speculate_k=2)
    eng = _engine()
    with pytest.raises(ValueError, match="single-role"):
        eng.set_role("prefill")
    with pytest.raises(ValueError):
        AdapterSpec(slots=2, rank=0)
    reg = eng.adapters
    with pytest.raises(ValueError, match="qkv_w"):
        reg.load(1, {"qkv_w": _delta(1)["out_w"]})
    with pytest.raises(ValueError, match="rank"):
        reg.load(1, _delta(1, rank=9))     # exceeds the configured max 4


def test_non_resident_adapter_blocks_admission_until_load():
    """A request bound to a non-resident adapter queues and WAITS at
    admission (typed counter ticks); a hot load admits it at the next
    boundary — and its stream is exact."""
    profiler.reset_serving_counters()
    eng = _engine()
    req = serving.Request(np.arange(5, 12), max_new_tokens=5, adapter=2)
    eng.submit(req)
    for _ in range(3):
        eng.step()
    assert req.slot is None and eng.queue_depth == 1, \
        "non-resident adapter request was admitted"
    assert smetrics.serving_counters()["adapter_admit_blocked"] >= 1
    eng.load_adapter(2, _delta(2), alpha=8.0)
    results = eng.run()
    _check_bitwise(eng, [req], results)


def test_mutating_bound_adapter_refused_until_slot_frees():
    eng = _load_std(_engine())
    req = serving.Request(np.arange(3, 8), max_new_tokens=12, adapter=1)
    eng.submit(req)
    while req.slot is None:
        eng.step()
    for fn in (lambda: eng.evict_adapter(1),
               lambda: eng.swap_adapter(1, _delta(9)),
               lambda: eng.load_adapter(1, _delta(9))):
        with pytest.raises(RuntimeError, match="bound to running"):
            fn()
    eng.run()                       # stream finishes, slot frees
    eng.swap_adapter(1, _delta(9), alpha=8.0)
    eng.evict_adapter(1)


# ---------------------------------------------------------------------------
# scheduling: WFQ lanes by adapter, tenant default mapping


def test_wfq_lanes_rotate_across_adapters():
    """Scheduler(lane_key=) generalization: admission deficit-round-
    robins across ADAPTER lanes, weights keyed by the lane value (string
    spelling accepted for flag-file weights)."""
    sch = serving.Scheduler(buckets=(8,), priority=True,
                            tenant_weights={"1": 2},
                            lane_key=lambda r: r.adapter or 0)
    reqs = [serving.Request([1, 2], max_new_tokens=1, adapter=a)
            for a in (1, 1, 1, 2, 2)]
    for r in reqs:
        sch.submit(r)
    admitted, _ = sch.admit(5)
    assert [r.adapter for r in admitted] == [1, 1, 2, 1, 2], \
        "weight-2 lane 1 should serve two per rotation"


def test_wfq_adapter_engine_integration():
    """One hot adapter's burst cannot starve the others: everything
    completes, exactly."""
    eng = _load_std(_engine(priority=True, num_slots=2))
    rng = np.random.default_rng(13)
    reqs = _mixed_requests((1, 1, 1, 1, 2, 0, 2), rng)
    _check_bitwise(eng, reqs, eng.run(reqs))


def test_tenant_default_adapter_mapping():
    eng = _load_std(_engine(tenant_adapters={"acme": 1, "beta": 2}))
    r_acme = serving.Request(np.arange(4, 10), max_new_tokens=5,
                             tenant="acme")
    r_other = serving.Request(np.arange(4, 10), max_new_tokens=5,
                              tenant="nobody")
    r_expl = serving.Request(np.arange(4, 10), max_new_tokens=5,
                             tenant="acme", adapter=2)   # explicit id wins
    results = eng.run([r_acme, r_other, r_expl])
    assert results[r_acme.request_id].adapter == 1
    assert results[r_other.request_id].adapter == 0
    assert results[r_expl.request_id].adapter == 2
    _check_bitwise(eng, [r_acme, r_other, r_expl], results)


def test_resolve_tenant_adapters_flag_spellings():
    assert resolve_tenant_adapters(
        {"FLAGS_serving_tenant_adapters": {"acme": 1}}) == {"acme": 1}
    assert resolve_tenant_adapters(
        {"FLAGS_serving_tenant_adapters": "acme:1, beta:2"}) \
        == {"acme": 1, "beta": 2}
    assert resolve_tenant_adapters({}) == {}
    with pytest.raises(ValueError):
        resolve_tenant_adapters({"FLAGS_serving_tenant_adapters": "acme"})


# ---------------------------------------------------------------------------
# snapshots: kill-and-resume carries the adapter set (satellite 3)


@pytest.mark.parametrize("sampled", [False, True])
def test_kill_resume_carries_adapter_set_bitwise(sampled):
    """Mid-flight kill + restore on a FRESH engine: the resident adapter
    set, per-adapter versions and per-slot bindings ride the snapshot;
    every stream resumes bitwise."""
    eng = _load_std(_engine())
    rng = np.random.default_rng(17)
    reqs = _mixed_requests((1, 0, 2, 1), rng, sampled=sampled)
    for r in reqs:
        eng.submit(r)
    for _ in range(4):
        eng.step()
    assert eng.active_slots, "kill must land mid-traffic"
    state = eng.state_dict()
    pre = eng.pop_results()
    del eng                                  # the "kill"

    restored = _engine()                     # NOTE: no adapters loaded
    restored.load_state_dict(state)
    assert sorted(restored.adapters.resident_ids()) == [1, 2]
    results = restored.run()
    results.update(pre)
    _check_bitwise(restored, reqs, results)


def test_restore_refuses_adapter_capacity_mismatch():
    eng = _load_std(_engine())
    state = eng.state_dict()
    other = _engine(adapter_slots=5)
    with pytest.raises(ValueError, match="adapter"):
        other.load_state_dict(state)


def test_pre_adapter_snapshot_restores_on_adapter_engine_and_back():
    """Back-compat both ways: an adapter-less snapshot restores onto an
    adapter-less engine built from the same factory defaults, and the
    meta['adapters'] field defaults cleanly when absent."""
    plain = serving.Engine(params=_params(), config=CFG, num_slots=3,
                           max_seq_len=96, page_size=8, prefill_chunk=8,
                           kv_layout="paged")
    req = serving.Request(np.arange(3, 9), max_new_tokens=4)
    plain.submit(req)
    plain.step()
    state = plain.state_dict()
    # simulate a snapshot written before the adapter subsystem existed
    state["meta"].pop("adapters", None)
    state.pop("aid", None)
    plain2 = serving.Engine(params=_params(), config=CFG, num_slots=3,
                            max_seq_len=96, page_size=8, prefill_chunk=8,
                            kv_layout="paged")
    plain2.load_state_dict(state)
    res = plain2.run()
    assert res[req.request_id].tokens == _ref_tokens(req.prompt, 4)


# ---------------------------------------------------------------------------
# tensor-parallel: mixed-adapter batches bitwise at mp in {2, 4}


@pytest.mark.parametrize("mp", [2, 4])
def test_mp_mixed_adapter_bitwise_vs_single_chip(mp, devices8):
    """Deltas shard with the output channels (B slabs column-sharded,
    compose-before-gather): the mp engine's mixed-adapter streams are
    bitwise the single-chip references."""
    from paddle_tpu.distributed import env as dist_env
    try:
        eng = _load_std(_engine(mp=mp, num_slots=3))
        rng = np.random.default_rng(23)
        reqs = _mixed_requests((1, 0, 2, 1), rng)
        results = eng.run(reqs)
        # reference runs SINGLE-CHIP on host copies of the same slab
        # content (device_get is a gather — exact)
        slabs = {k: (np.asarray(jax.device_get(a)),
                     np.asarray(jax.device_get(b)))
                 for k, (a, b) in eng.adapters.device_slabs().items()}
        for r in reqs:
            aid = r.adapter or 0
            ref = _ref_tokens(r.prompt, r.max_new_tokens,
                              adapters=(aid, slabs))
            got = results[r.request_id].tokens
            assert got == ref[:len(got)] and got, \
                f"mp={mp} adapter {aid}: {got} != {ref}"
    finally:
        paddle.set_flags({"FLAGS_comm_backend": "", "FLAGS_serving_mp": 0})
        dist_env.set_mesh(None)


# ---------------------------------------------------------------------------
# supervisor: fleet-level ops, respawn and rolling restart carry the set


def _factory():
    return _engine(num_slots=3)


def test_supervisor_fleet_adapter_ops_survive_replica_kill(tmp_path):
    """sup.load_adapter applies fleet-wide and rides the live set: a
    replica killed mid-decode respawns SERVING the adapters; every
    mixed-adapter request completes bitwise with zero drops."""
    profiler.reset_serving_counters()
    sup = serving.ServingSupervisor(_factory, num_replicas=2,
                                    snapshot_dir=tmp_path, snapshot_every=2)
    sup.load_adapter(1, _delta(1), alpha=8.0)
    sup.load_adapter(2, _delta(2), alpha=8.0)
    rng = np.random.default_rng(29)
    reqs = _mixed_requests((1, 2, 0, 1, 2, 1), rng)
    with fi.inject(fi.FaultPlan(kill_at_decode_step=3,
                                kill_engine_tag="replica0")):
        results = sup.run(reqs)
        assert fi.stats()["serving_kills"] == 1
    c = smetrics.serving_counters()
    assert c["dropped"] == 0 and c["respawns"] >= 1
    # fleet-level ops count once, not per replica
    assert c["adapter_loads"] == 2
    eng = next(r.engine for r in sup._replicas if r.engine is not None)
    assert sorted(eng.adapters.resident_ids()) == [1, 2]
    _check_bitwise(eng, reqs, results)
    assert sup.telemetry()["adapters_live"] == 2


def test_supervisor_rolling_restart_and_evict_swap():
    sup = serving.ServingSupervisor(_factory, num_replicas=2)
    sup.load_adapter(1, _delta(1), alpha=8.0)
    sup.load_adapter(2, _delta(2), alpha=8.0)
    sup.rolling_restart()
    for rep in sup._replicas:
        assert sorted(rep.engine.adapters.resident_ids()) == [1, 2]
    sup.swap_adapter(1, _delta(51), alpha=8.0)
    sup.evict_adapter(2)
    for rep in sup._replicas:
        assert rep.engine.adapters.resident_ids() == (1,)
    # a rolling restart AFTER the evict must not resurrect adapter 2
    sup.rolling_restart()
    for rep in sup._replicas:
        assert rep.engine.adapters.resident_ids() == (1,)
    req = serving.Request(np.arange(5, 11), max_new_tokens=5, adapter=1)
    results = sup.run([req])
    eng = sup._replicas[0].engine
    _check_bitwise(eng, [req], results)


# ---------------------------------------------------------------------------
# observability: gauges, token shares, summary segment, export round-trip


def test_adapter_metrics_and_summary_segment():
    profiler.reset_serving_counters()
    eng = _load_std(_engine())
    rng = np.random.default_rng(31)
    eng.run(_mixed_requests((1, 2, 0, 1), rng))
    c = smetrics.serving_counters()
    assert c["adapters_resident"] == 2
    assert c["adapter_delta_bytes"] == eng.adapters.delta_bytes() > 0
    assert c["adapter_tokens_1"] > 0 and c["adapter_tokens_2"] > 0
    shares = [v for k, v in c.items()
              if k.startswith("adapter_token_share_")]
    assert abs(sum(shares) - 1.0) < 1e-9
    summary = smetrics.serving_summary()
    assert "adapters: 2/3 resident" in summary
    assert "tok-share" in summary
    # export/import carries the per-adapter tallies (snapshot metrics)
    state = smetrics.export_state()
    profiler.reset_serving_counters()
    assert "adapter_tokens_1" not in smetrics.serving_counters()
    smetrics.import_state(state)
    assert smetrics.serving_counters()["adapter_tokens_1"] \
        == c["adapter_tokens_1"]


def test_request_trace_carries_adapter_span():
    eng = _load_std(_engine(trace=True))
    req = serving.Request(np.arange(2, 8), max_new_tokens=3, adapter=1)
    eng.run([req])
    ad = [e for e in req.trace.spans if e["name"] == "adapter"]
    assert ad and ad[0]["adapter_id"] == 1


def test_registry_hbm_accounting_and_state_roundtrip():
    spec = AdapterSpec(slots=4, rank=8)
    reg = AdapterRegistry(CFG, spec)
    assert reg.delta_bytes() == 0
    reg.load(2, _delta(2, rank=8), alpha=16.0)
    assert reg.delta_bytes() == reg.row_bytes() > 0
    assert reg.slab_bytes() >= (spec.slots + 1) * reg.row_bytes()
    state = reg.state_dict()
    reg2 = AdapterRegistry(CFG, spec)
    reg2.load_state_dict(state)
    assert reg2.resident_ids() == (2,)
    for name in ("out_w", "up_w", "down_w"):
        a1, b1 = reg._host[name]
        a2, b2 = reg2._host[name]
        np.testing.assert_array_equal(a1, a2)
        np.testing.assert_array_equal(b1, b2)


# ---------------------------------------------------------------------------
# smoke rung (tools_serving_smoke --adapters)


def _load_smoke():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "tools_serving_smoke",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools_serving_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_smoke_adapter_deterministic_subrung():
    """tools_serving_smoke's many-model rung in deterministic tiny mode:
    mixed-adapter parity vs solo references, frozen executables across
    hot adapter ops, and the HBM ledger — no wall-clock gates."""
    mod = _load_smoke()
    out = mod.run_adapter_rung(deterministic=True)
    assert out["parity"]
    assert out["trace_frozen"]
    assert out["hbm"]["adapter_slab_bytes"] > 0
    # N low-rank variants must cost a small fraction of N weight copies
    assert out["hbm"]["ratio"] < 0.5
    assert out["adapter_ops"]["swaps"] >= 1 and out["adapter_ops"]["evicts"] >= 1


@pytest.mark.slow
def test_smoke_adapter_beats_swap_per_tenant():
    mod = _load_smoke()
    out = mod.run_adapter_rung(quick=True)
    assert out["speedup"] >= 1.15
    assert out["hbm"]["ratio"] < 0.5
