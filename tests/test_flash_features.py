"""Flash-attention kernel feature tests (interpret mode on CPU): segment
ids (padding/varlen), additive bias/mask, varlen API, and their gradients.

Dropout is TPU-PRNG-only (interpret mode cannot emulate it) and is covered
by the on-hardware bench/probe path plus the clear-error test here.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.pallas_kernels.flash_attention import (
    _pallas_forward, flash_attention_varlen, flash_supported, pick_block)


def dense_ref(q, k, v, causal=False, bias=None, qseg=None, kseg=None,
              scale=None):
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
    s = scale if scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * s
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if qseg is not None:
        m = qseg[:, None, :, None] == kseg[:, None, None, :]
        logits = jnp.where(m, logits, -1e30)
    if causal:
        cm = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        logits = jnp.where(cm, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf)


def _make(B=2, S=256, H=2, D=64, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return tuple(jax.random.normal(kk, (B, S, H, D), jnp.float32)
                 for kk in ks)


def test_segment_ids_match_masked_dense():
    q, k, v = _make()
    B, S = q.shape[:2]
    seg = jnp.concatenate([jnp.zeros((B, S // 2), jnp.int32),
                           jnp.ones((B, S // 2), jnp.int32)], axis=1)
    out = _pallas_forward(q, k, v, causal=False, block_q=128, block_k=128,
                          segment_ids=(seg, seg), interpret=True)
    ref = dense_ref(q, k, v, qseg=seg, kseg=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_segment_ids_padding_rows_zero():
    """Rows whose q segment matches nothing must produce zeros (the varlen
    padding contract)."""
    q, k, v = _make(B=1)
    S = q.shape[1]
    qseg = jnp.where(jnp.arange(S) < 200, 0, -1)[None].astype(jnp.int32)
    kseg = jnp.where(jnp.arange(S) < 200, 0, -2)[None].astype(jnp.int32)
    out = _pallas_forward(q, k, v, causal=False, block_q=128, block_k=128,
                          segment_ids=(qseg, kseg), interpret=True)
    np.testing.assert_allclose(np.asarray(out[0, 200:]), 0.0, atol=1e-6)
    ref = dense_ref(q[:, :200], k[:, :200], v[:, :200])
    np.testing.assert_allclose(np.asarray(out[0, :200]),
                               np.asarray(ref[0]), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("bias_shape", [(2, 2), (2, 1), (1, 1)])
def test_bias_matches_dense(bias_shape):
    q, k, v = _make()
    S = q.shape[1]
    bias = jax.random.normal(jax.random.key(9), bias_shape + (S, S),
                             jnp.float32)
    out = _pallas_forward(q, k, v, causal=True, block_q=128, block_k=128,
                          bias=bias, interpret=True)
    ref = dense_ref(q, k, v, causal=True, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)


def test_bias_and_segment_grads_match_dense():
    """Gradients through the full custom_vjp with bias + segments."""
    from paddle_tpu.ops.pallas_kernels.flash_attention import (
        flash_attention_bshd)
    q, k, v = _make(B=1, S=256)
    S = q.shape[1]
    bias = jax.random.normal(jax.random.key(5), (1, 1, S, S), jnp.float32)
    seg = jnp.where(jnp.arange(S) < 192, 0, 1)[None].astype(jnp.int32)

    def loss_flash(q_, k_, v_):
        o = flash_attention_bshd(q_, k_, v_, False, bias, (seg, seg))
        return jnp.sum(o ** 2)

    def loss_dense(q_, k_, v_):
        o = dense_ref(q_, k_, v_, bias=bias, qseg=seg, kseg=seg)
        return jnp.sum(o ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_varlen_matches_per_segment_dense():
    cu = jnp.array([0, 100, 260, 512], jnp.int32)
    T, H, D = 512, 2, 64
    ks = jax.random.split(jax.random.key(2), 3)
    qp, kp, vp = (jax.random.normal(kk, (T, H, D), jnp.float32) for kk in ks)
    out = flash_attention_varlen(qp, kp, vp, cu, cu, causal=True, block=128)
    for i in range(3):
        a, b = int(cu[i]), int(cu[i + 1])
        ref = dense_ref(qp[None, a:b], kp[None, a:b], vp[None, a:b],
                        causal=True)[0]
        np.testing.assert_allclose(np.asarray(out[a:b]), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_varlen_grads_flow():
    cu = jnp.array([0, 100, 256], jnp.int32)
    T, H, D = 256, 2, 64
    ks = jax.random.split(jax.random.key(4), 3)
    qp, kp, vp = (jax.random.normal(kk, (T, H, D), jnp.float32) for kk in ks)

    def loss(q_, k_, v_):
        return jnp.sum(
            flash_attention_varlen(q_, k_, v_, cu, cu, causal=True,
                                   block=128) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(qp, kp, vp)

    def dense_loss(q_, k_, v_):
        tot = 0.0
        for i in range(2):
            a, b = int(cu[i]), int(cu[i + 1])
            tot = tot + jnp.sum(dense_ref(q_[None, a:b], k_[None, a:b],
                                          v_[None, a:b], causal=True) ** 2)
        return tot

    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(qp, kp, vp)
    for a, b in zip(g, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_functional_flash_attn_unpadded():
    """The public API (composed fallback path on CPU) matches per-segment
    dense attention."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    cu = np.array([0, 60, 160], np.int32)
    T, H, D = 160, 2, 32
    rng = np.random.default_rng(0)
    q, k, v = (paddle.to_tensor(rng.standard_normal((T, H, D), np.float32))
               for _ in range(3))
    out, _ = F.flash_attn_unpadded(q, k, v, paddle.to_tensor(cu),
                                   paddle.to_tensor(cu), causal=False)
    qn, kn, vn = (np.asarray(t.numpy()) for t in (q, k, v))
    for i in range(2):
        a, b = int(cu[i]), int(cu[i + 1])
        ref = dense_ref(jnp.asarray(qn[None, a:b]), jnp.asarray(kn[None, a:b]),
                        jnp.asarray(vn[None, a:b]))[0]
        np.testing.assert_allclose(np.asarray(out.numpy())[a:b],
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_dropout_interpret_raises_clearly():
    q, k, v = _make(B=1)
    with pytest.raises(NotImplementedError, match="TPU PRNG"):
        _pallas_forward(q, k, v, causal=False, dropout_p=0.5, dropout_seed=1,
                        interpret=True)


def test_pick_block_and_gating():
    assert pick_block(2048) == 256
    assert pick_block(384) == 128
    assert pick_block(100) is None
    assert pick_block(512, preferred=512) == 512
    # off-TPU everything routes to XLA
    assert not flash_supported((1, 2048, 2, 64))
