"""Fault-tolerant training runtime: compiled anomaly guard
(FLAGS_anomaly_policy), hardened CheckpointManager (CRC manifest,
quarantine+fallback, retry/backoff, rename-aside publish, SIGTERM flush),
TrainStep exact-resume state_dict, deterministic fault injection, and the
satellite fixes (GradScaler double-unscale guard, DataLoader timeout and
position state, elastic seed-class coverage)."""
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import elastic
from paddle_tpu.distributed import env as dist_env
from paddle_tpu.incubate.checkpoint import (
    CheckpointCorruptError, CheckpointManager, Preempted, ckpt_counters)
from paddle_tpu.io import DataLoader
from paddle_tpu.jit.train_step import anomaly_counters, reset_anomaly_counters
from paddle_tpu.utils import fault_injection as fi


_DEFAULT_FLAGS = {
    "FLAGS_anomaly_policy": "off",
    "FLAGS_anomaly_max_bad_steps": 3,
    "FLAGS_grad_comm": "auto",
    "FLAGS_weight_update_sharding": False,
    "FLAGS_allreduce_dtype": "float32",
}

WUS = {"FLAGS_grad_comm": "on", "FLAGS_weight_update_sharding": True}


@pytest.fixture(autouse=True)
def _reset():
    yield
    paddle.set_flags(dict(_DEFAULT_FLAGS))
    dist_env.set_mesh(None)
    fi.deactivate()


def _model(seed=7, width=8, dropout=False):
    paddle.seed(seed)
    layers = [nn.Linear(width, width), nn.ReLU()]
    if dropout:
        layers.append(nn.Dropout(0.25))
    layers.append(nn.Linear(width, 4))
    return nn.Sequential(*layers)


def _step(flags=None, seed=7, mesh=None, k=1, dropout=False, width=8,
          sched=False):
    paddle.set_flags(dict(_DEFAULT_FLAGS))
    if flags:
        paddle.set_flags(flags)
    m = _model(seed=seed, width=width, dropout=dropout)
    lr = paddle.optimizer.lr.NaturalExpDecay(0.01, gamma=0.1) if sched \
        else 0.01
    opt = paddle.optimizer.AdamW(lr, parameters=m.parameters())
    return paddle.jit.TrainStep(m, nn.MSELoss(), opt, mesh=mesh,
                                accumulate_steps=k)


def _data(n=8, width=8, rows=4, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, rows, width)).astype(np.float32),
            rng.standard_normal((n, rows, 4)).astype(np.float32))


def _run(step, X, Y, lo=0, hi=None, lr_step=False):
    hi = len(X) if hi is None else hi
    losses = []
    for i in range(lo, hi):
        losses.append(step(paddle.to_tensor(X[i]), paddle.to_tensor(Y[i])))
        if lr_step:
            step.optimizer._learning_rate.step()
    return {n: np.asarray(a) for n, a in step.params.items()}, losses


# ---------------------------------------------------------------------------
# compiled anomaly guard
# ---------------------------------------------------------------------------


def test_guard_off_is_default_and_adds_no_host_work():
    reset_anomaly_counters()
    X, Y = _data(3)
    step = _step()
    _run(step, X, Y)
    assert step._anomaly is None
    c = anomaly_counters()
    assert c["steps"] == 0 and c["host_syncs"] == 0  # policy layer inactive


def test_guard_on_no_faults_is_bitwise_identical_single_device():
    X, Y = _data(5)
    p_off, _ = _run(_step(), X, Y)
    p_on, _ = _run(_step({"FLAGS_anomaly_policy": "skip"}), X, Y)
    for n in p_off:
        np.testing.assert_array_equal(p_off[n], p_on[n]), n


def test_guard_skips_update_on_poisoned_step_and_recovers():
    X, Y = _data(6)
    reset_anomaly_counters()
    step = _step({"FLAGS_anomaly_policy": "skip"})
    with fi.inject(fi.FaultPlan(nan_at_steps=[2])):
        _run(step, X, Y, hi=2)
        p_before = {n: np.asarray(a) for n, a in step.params.items()}
        loss = step(paddle.to_tensor(X[2]), paddle.to_tensor(Y[2]))
        assert not step.last_step_ok
        assert not np.isfinite(np.asarray(loss.numpy()))
        for n in p_before:  # params/slots untouched by the bad step
            np.testing.assert_array_equal(
                p_before[n], np.asarray(step.params[n]))
        p_after, losses = _run(step, X, Y, lo=3)
    assert step.last_step_ok
    assert all(np.isfinite(np.asarray(a)).all() for a in p_after.values())
    assert fi.stats()["poisoned_steps"] == 1
    c = anomaly_counters()
    assert c["bad_steps"] == 1 and c["skipped_updates"] == 1


def test_guard_single_host_sync_per_step():
    """The zero-extra-sync contract: one combined (loss, step_ok) fetch per
    guarded step — host_syncs == steps exactly, and the returned loss is
    already host-resident."""
    reset_anomaly_counters()
    X, Y = _data(4)
    step = _step({"FLAGS_anomaly_policy": "skip"})
    _run(step, X, Y)
    c = anomaly_counters()
    assert c["steps"] == 4 and c["host_syncs"] == 4


def test_guard_skip_poisoned_step_matches_skipping_the_batch():
    """Skip semantics are exact: a run whose step k is poisoned (and
    skipped) ends bitwise identical to a run that never saw step k's batch
    but consumed the same RNG stream."""
    X, Y = _data(5)
    step_a = _step({"FLAGS_anomaly_policy": "skip"})
    with fi.inject(fi.FaultPlan(nan_at_steps=[2])):
        p_a, _ = _run(step_a, X, Y)
    # reference: same stream, but batch 2's update manually elided by
    # feeding it as a poisoned batch too — instead run steps 0,1,3,4 with
    # the key stream burning one key at step 2
    from paddle_tpu.framework import random as frandom
    step_b = _step({"FLAGS_anomaly_policy": "skip"})
    _run(step_b, X, Y, hi=2)
    frandom.advance(1)  # the skipped step still consumed its key
    p_b, _ = _run(step_b, X, Y, lo=3)
    for n in p_a:
        np.testing.assert_array_equal(p_a[n], p_b[n]), n


def test_guard_accum_defers_sync_to_fire_boundary():
    """Under accumulation the micro flags ride to the boundary: one host
    sync per UPDATE step, not per micro-step — and a bad micro (which only
    drops its contribution; the boundary update still runs) counts toward
    bad_steps but never skipped_updates."""
    reset_anomaly_counters()
    X, Y = _data(6)
    step = _step({"FLAGS_anomaly_policy": "skip"}, k=3)
    with fi.inject(fi.FaultPlan(nan_at_steps=[1])):
        _run(step, X, Y)
    c = anomaly_counters()
    assert c["steps"] == 6 and c["host_syncs"] == 2  # two fire boundaries
    assert c["bad_steps"] == 1 and c["skipped_updates"] == 0
    assert step._pending_ok == []


def test_guard_rejects_unknown_policy():
    X, Y = _data(1)
    step = _step({"FLAGS_anomaly_policy": "explode"})
    with pytest.raises(ValueError, match="anomaly_policy"):
        step(paddle.to_tensor(X[0]), paddle.to_tensor(Y[0]))


# ---------------------------------------------------------------------------
# anomaly guard under the explicit grad-comm schedule (dp=8 mesh)
# ---------------------------------------------------------------------------


def test_guard_wus_no_faults_matches_unguarded(devices8):
    X, Y = _data(4, rows=16)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    p_off, _ = _run(_step(WUS, mesh=mesh), X, Y)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    p_on, _ = _run(_step(dict(WUS, FLAGS_anomaly_policy="skip"), mesh=mesh),
                   X, Y)
    for n in p_off:
        # the guard's in-graph isfinite blocks one XLA division fusion, so
        # parity is to rounding (flags-OFF stays bitwise vs main)
        np.testing.assert_allclose(p_off[n], p_on[n], rtol=1e-5, atol=1e-7)


def test_guard_wus_accum_poisoned_micro_is_dropped(devices8):
    """Under weight-update sharding + accumulation, the shard-space check
    psums the verdict: a poisoned micro-batch contributes nothing to the
    packed accumulator and training stays finite."""
    reset_anomaly_counters()
    X, Y = _data(6, rows=16)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    step = _step(dict(WUS, FLAGS_anomaly_policy="skip"), mesh=mesh, k=2)
    with fi.inject(fi.FaultPlan(nan_at_steps=[2])):
        p, _ = _run(step, X, Y)
    assert not np.isfinite(X[2]).all() or True  # plan poisoned in place
    assert all(np.isfinite(np.asarray(a)).all() for a in p.values())
    c = anomaly_counters()
    assert c["bad_steps"] == 1 and c["steps"] == 6
    # packed slots stayed finite too
    for name, sl in step.opt_state["slots"].items():
        for k_, arr in sl.items():
            assert np.isfinite(np.asarray(arr)).all(), (name, k_)


def test_guard_composed_dp_mp_poisoned_step(devices8):
    """Guard composes with an active mp axis (partial-manual grad_comm):
    the verdict psums over the dp axis only, mp stays GSPMD-auto."""
    from paddle_tpu.distributed.fleet.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)
    paddle.set_flags(dict(_DEFAULT_FLAGS))
    paddle.set_flags(dict(WUS, FLAGS_anomaly_policy="skip"))
    mesh = dist_env.create_hybrid_mesh(dp=2, mp=4)
    paddle.seed(7)
    m = nn.Sequential(ColumnParallelLinear(16, 32, gather_output=False),
                      nn.ReLU(),
                      RowParallelLinear(32, 16, input_is_parallel=True),
                      nn.Linear(16, 8))
    opt = paddle.optimizer.AdamW(0.01, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt, mesh=mesh)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 16)).astype(np.float32)
    y = rng.standard_normal((8, 8)).astype(np.float32)
    with fi.inject(fi.FaultPlan(nan_at_steps=[1])):
        for _ in range(3):
            step(paddle.to_tensor(x), paddle.to_tensor(y))
    assert step._gc_cfg is not None and step._gc_cfg.auto_axes == ("mp",)
    assert all(np.isfinite(np.asarray(a)).all()
               for a in step.params.values())
    assert anomaly_counters()["bad_steps"] >= 1


# ---------------------------------------------------------------------------
# rollback policy
# ---------------------------------------------------------------------------


def test_rollback_restores_checkpoint_after_k_bad_steps(tmp_path):
    reset_anomaly_counters()
    X, Y = _data(9)
    step = _step({"FLAGS_anomaly_policy": "rollback",
                  "FLAGS_anomaly_max_bad_steps": 2})
    mgr = CheckpointManager(tmp_path, async_save=False)
    events = []
    step.attach_checkpoint(mgr, save_every=2,
                           on_rollback=lambda s, t: events.append((s, t)))
    with fi.inject(fi.FaultPlan(nan_at_steps=[4, 5])):
        p, _ = _run(step, X, Y, hi=8)
    c = anomaly_counters()
    assert c["rollbacks"] == 1 and c["bad_steps"] == 2
    # restored from the step-4 checkpoint, resumed past the poison batches
    assert events == [(4, 6)]
    assert all(np.isfinite(np.asarray(a)).all() for a in p.values())
    assert step._bad_streak == 0 and step.last_step_ok


def test_rollback_does_not_rewind_attached_loader(tmp_path):
    """The data stream keeps moving forward through a rollback: the
    checkpointed loader position must NOT be re-installed (that would
    re-serve batches the fast-forwarded RNG already accounted past)."""
    reset_anomaly_counters()
    X, Y = _data(8)
    step = _step({"FLAGS_anomaly_policy": "rollback",
                  "FLAGS_anomaly_max_bad_steps": 2})
    loader = DataLoader(list(range(20)), batch_size=2)
    loader._served = 4  # position at checkpoint time
    mgr = CheckpointManager(tmp_path, async_save=False)
    step.attach_checkpoint(mgr, save_every=2)
    step.attach_loader(loader)
    with fi.inject(fi.FaultPlan(nan_at_steps=[4, 5])):
        _run(step, X, Y, hi=7)
    assert anomaly_counters()["rollbacks"] == 1
    assert loader._resume_skip == 0  # not rewound by the rollback
    # but an explicit load_state_dict (real resume) does restore it
    step.load_state_dict(mgr.restore())
    assert loader._resume_skip == 4


def test_rollback_without_checkpoint_raises():
    X, Y = _data(4)
    step = _step({"FLAGS_anomaly_policy": "rollback",
                  "FLAGS_anomaly_max_bad_steps": 1})
    with fi.inject(fi.FaultPlan(nan_at_steps=[1])):
        step(paddle.to_tensor(X[0]), paddle.to_tensor(Y[0]))
        with pytest.raises(elastic.NonFiniteError, match="rollback"):
            step(paddle.to_tensor(X[1]), paddle.to_tensor(Y[1]))


# ---------------------------------------------------------------------------
# exact resume: TrainStep.state_dict / load_state_dict
# ---------------------------------------------------------------------------


def test_exact_resume_bitwise_with_dropout_and_lr_scheduler(tmp_path):
    """The bitwise interrupted-vs-uninterrupted trajectory test: dropout
    exercises the RNG stream capture, NaturalExpDecay the scheduler step."""
    X, Y = _data(8)
    golden, _ = _run(_step(dropout=True, sched=True), X, Y, lr_step=True)

    step_a = _step(dropout=True, sched=True)
    _run(step_a, X, Y, hi=4, lr_step=True)
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(4, step_a.state_dict())
    del step_a  # process dies here

    step_b = _step(seed=999, dropout=True, sched=True)  # different init!
    step_b.load_state_dict(mgr.restore())
    assert step_b._step == 4
    resumed, _ = _run(step_b, X, Y, lo=4, lr_step=True)
    for n in golden:
        np.testing.assert_array_equal(golden[n], resumed[n]), n
    # scheduler position restored too
    assert step_b.optimizer._learning_rate.last_epoch == 8


def test_exact_resume_scaler_and_loader_ride_along(tmp_path):
    from paddle_tpu.amp import GradScaler
    X, Y = _data(3)
    step = _step()
    scaler = GradScaler(init_loss_scaling=2.0 ** 5)
    scaler._good_steps = 7
    loader = DataLoader(list(range(10)), batch_size=2)
    loader._served = 3
    step.attach_scaler(scaler).attach_loader(loader)
    _run(step, X, Y)
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(3, step.state_dict())

    step2 = _step(seed=1)
    scaler2, loader2 = GradScaler(), DataLoader(list(range(10)), batch_size=2)
    step2.attach_scaler(scaler2).attach_loader(loader2)
    step2.load_state_dict(mgr.restore())
    assert scaler2.get_init_loss_scaling() == 2.0 ** 5
    assert scaler2._good_steps == 7
    assert loader2._resume_skip == 3


def test_exact_resume_wus_accum_packed_slots(tmp_path, devices8):
    """Kill-and-resume equivalence under FLAGS_weight_update_sharding with
    packed dp-sharded optimizer slots and accumulate_steps=2 — the save
    lands MID accumulation window and the restored slots go straight back
    to their packed dp-sharded placement."""
    X, Y = _data(6, rows=16)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    golden, _ = _run(_step(WUS, mesh=mesh, k=2), X, Y)

    mesh = dist_env.create_hybrid_mesh(dp=8)
    step_a = _step(WUS, mesh=mesh, k=2)
    _run(step_a, X, Y, hi=3)  # 3 % k != 0: mid-window, accumulator live
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(3, step_a.state_dict())
    # the checkpoint stores the slots packed — never the materialized form
    st = mgr.restore()
    for name, sl in st["opt_state"]["slots"].items():
        for k_, arr in sl.items():
            assert np.asarray(arr).ndim == 2 and np.asarray(arr).shape[0] == 8
    del step_a

    mesh = dist_env.create_hybrid_mesh(dp=8)
    step_b = _step(WUS, mesh=mesh, k=2)
    step_b.load_state_dict(st)
    resumed, _ = _run(step_b, X, Y, lo=3)
    for n in golden:
        np.testing.assert_array_equal(golden[n], resumed[n]), n
    for name, sl in step_b.opt_state["slots"].items():
        for k_, arr in sl.items():
            assert arr.ndim == 2 and arr.shape[0] == 8, (name, k_)
            assert arr.sharding.spec[0] == "dp", (name, k_)


def test_exact_resume_after_simulated_preemption(tmp_path):
    """Acceptance path: a run interrupted by simulated preemption resumes
    from the latest checkpoint and reproduces the uninterrupted trajectory
    bitwise (the preempting step re-executes)."""
    X, Y = _data(8)
    golden, _ = _run(_step(), X, Y)

    step_a = _step()
    mgr = CheckpointManager(tmp_path, async_save=False)
    step_a.attach_checkpoint(mgr, save_every=2)
    with pytest.raises(fi.Preemption):
        with fi.inject(fi.FaultPlan(preempt_at_step=5)):
            _run(step_a, X, Y)
    del step_a

    step_b = _step(seed=123)
    step_b.load_state_dict(mgr.restore())
    start = step_b._step
    assert start == 4  # latest periodic save before the preemption
    resumed, _ = _run(step_b, X, Y, lo=start)
    for n in golden:
        np.testing.assert_array_equal(golden[n], resumed[n]), n


# ---------------------------------------------------------------------------
# hardened CheckpointManager
# ---------------------------------------------------------------------------


def test_ckpt_crc_corruption_quarantined_with_fallback(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(1, {"w": np.arange(8.0)})
    mgr.save(2, {"w": np.ones(8)})
    p = tmp_path / "step_2" / "state.pdckpt"
    raw = bytearray(p.read_bytes())
    raw[-16] ^= 0xFF
    p.write_bytes(bytes(raw))
    got = mgr.restore()  # falls back past the rotten step
    np.testing.assert_array_equal(got["w"], np.arange(8.0))
    assert mgr.all_steps() == [1]
    assert (tmp_path / "step_2.corrupt").is_dir()  # kept for postmortem


def test_ckpt_explicit_corrupt_step_raises(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(3, {"w": np.zeros(4)})
    p = tmp_path / "step_3" / "state.pdckpt"
    p.write_bytes(b"rotten")
    with pytest.raises(CheckpointCorruptError):
        mgr.restore(3)
    assert (tmp_path / "step_3.corrupt").is_dir()


def test_ckpt_all_corrupt_returns_none(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(1, {"w": np.zeros(4)})
    (tmp_path / "step_1" / "state.pdckpt").write_bytes(b"x")
    assert mgr.restore() is None


def test_ckpt_transient_read_error_does_not_quarantine(tmp_path,
                                                       monkeypatch):
    """An OSError while READING (flaky NFS) must not condemn good bytes:
    the read retries with backoff, and a persistently unreadable latest
    step is skipped — still on disk, not renamed *.corrupt."""
    from paddle_tpu.incubate import checkpoint as ckpt_mod
    mgr = CheckpointManager(tmp_path, async_save=False, retries=2,
                            retry_backoff=0.01)
    mgr.save(1, {"w": np.arange(4.0)})
    mgr.save(2, {"w": np.ones(4)})
    real_load = ckpt_mod.fio.load
    flaky = {"fails": 1}

    def flaky_load(path, **kw):
        if flaky["fails"] > 0:
            flaky["fails"] -= 1
            raise OSError("ESTALE")
        return real_load(path, **kw)

    monkeypatch.setattr(ckpt_mod.fio, "load", flaky_load)
    got = mgr.restore()  # one transient failure -> retried, step 2 intact
    np.testing.assert_array_equal(got["w"], 1.0)
    assert mgr.all_steps() == [1, 2]

    flaky["fails"] = 10 ** 9  # step 2 persistently unreadable
    got = mgr.restore()
    assert got is None  # every step unreadable, nothing quarantined
    monkeypatch.undo()
    assert sorted(p.name for p in tmp_path.iterdir()) == ["step_1", "step_2"]
    np.testing.assert_array_equal(mgr.restore()["w"], 1.0)  # fs recovered


def test_ckpt_transient_io_retries_with_backoff(tmp_path):
    before = ckpt_counters()["save_retries"]
    mgr = CheckpointManager(tmp_path, async_save=False, retries=3,
                            retry_backoff=0.01)
    with fi.inject(fi.FaultPlan(io_error_on_writes=[1, 2])):
        mgr.save(5, {"w": np.zeros(4)})
    assert mgr.latest_step() == 5
    assert ckpt_counters()["save_retries"] - before == 2


def test_ckpt_exhausted_retries_surface(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False, retries=1,
                            retry_backoff=0.01)
    with fi.inject(fi.FaultPlan(io_error_on_writes=[1, 2])):
        with pytest.raises(OSError, match="injected"):
            mgr.save(1, {"w": np.zeros(4)})
    # async saves surface the error on the next wait()
    mgr2 = CheckpointManager(tmp_path, async_save=True, retries=0,
                             retry_backoff=0.01)
    with fi.inject(fi.FaultPlan(io_error_on_writes=[1])):
        mgr2.save(2, {"w": np.zeros(4)})
        with pytest.raises(OSError, match="injected"):
            mgr2.wait()


def test_ckpt_overwrite_never_deletes_only_copy(tmp_path):
    """Replacing an existing step dir goes rename-aside -> publish -> drop;
    a crash between the renames is healed by _recover (both survivor
    shapes: complete .tmp adopted, else .old rolled back)."""
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(7, {"w": np.zeros(3)})
    mgr.save(7, {"w": np.ones(3)})  # overwrite same step
    np.testing.assert_array_equal(mgr.restore(7)["w"], 1.0)
    assert not (tmp_path / "step_7.old").exists()

    # crash shape 1: aside exists, no final, no tmp -> old copy re-adopted
    os.rename(tmp_path / "step_7", tmp_path / "step_7.old")
    m2 = CheckpointManager(tmp_path, async_save=False)
    assert m2.all_steps() == [7]
    np.testing.assert_array_equal(m2.restore(7)["w"], 1.0)

    # crash shape 2: aside + complete tmp -> the NEW bytes win
    mgr3 = CheckpointManager(tmp_path / "b", async_save=False)
    mgr3.save(9, {"w": np.zeros(2)})
    os.rename(tmp_path / "b" / "step_9", tmp_path / "b" / "step_9.old")
    import shutil
    shutil.copytree(tmp_path / "b" / "step_9.old",
                    tmp_path / "b" / "step_9.tmp")
    m4 = CheckpointManager(tmp_path / "b", async_save=False)
    assert m4.all_steps() == [9]
    assert not (tmp_path / "b" / "step_9.tmp").exists()

    # crash shape 3: aside + TORN tmp (state file but no manifest, i.e.
    # killed mid-write) -> the good old copy must win, not the torn bytes
    mgr5 = CheckpointManager(tmp_path / "c", async_save=False)
    mgr5.save(4, {"w": np.full(2, 5.0)})
    os.rename(tmp_path / "c" / "step_4", tmp_path / "c" / "step_4.old")
    os.makedirs(tmp_path / "c" / "step_4.tmp")
    (tmp_path / "c" / "step_4.tmp" / "state.pdckpt").write_bytes(b"torn")
    m6 = CheckpointManager(tmp_path / "c", async_save=False)
    np.testing.assert_array_equal(m6.restore(4)["w"], 5.0)


def test_ckpt_prune_and_all_steps_tolerate_races(tmp_path):
    mgr = CheckpointManager(tmp_path, keep_last_n=2, async_save=False)
    for s in range(4):
        mgr.save(s, {"x": np.zeros(2)})
    assert mgr.all_steps() == [2, 3]
    # concurrent deletion between listdir and rmtree: losing the race is ok
    import shutil
    shutil.rmtree(tmp_path / "step_2")
    mgr._prune()
    assert mgr.all_steps() == [3]
    # directory swept away entirely
    gone = CheckpointManager(tmp_path / "gone", async_save=False)
    shutil.rmtree(tmp_path / "gone")
    assert gone.all_steps() == []
    assert gone.latest_step() is None


def test_ckpt_sigterm_preemption_hook(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False)
    prev_handler = signal.getsignal(signal.SIGTERM)
    state = {"w": np.full(3, 9.0), "step": 11}
    mgr.install_preemption_hook(lambda: state, step_fn=lambda: 11)
    try:
        with pytest.raises(Preempted, match="flushed"):
            signal.raise_signal(signal.SIGTERM)
    finally:
        mgr.remove_preemption_hook()
    assert signal.getsignal(signal.SIGTERM) is prev_handler
    assert mgr.preempted
    got = mgr.restore(11)
    np.testing.assert_array_equal(got["w"], 9.0)
    assert ckpt_counters()["preempt_saves"] >= 1


# ---------------------------------------------------------------------------
# GradScaler double-unscale guard
# ---------------------------------------------------------------------------


def test_gradscaler_second_unscale_is_noop_until_update():
    from paddle_tpu.amp import GradScaler
    paddle.seed(5)
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    scaler = GradScaler(init_loss_scaling=2.0 ** 8)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))

    def one_step(double_unscale):
        opt.clear_grad()
        out = net(x)
        loss = (out * out).mean()
        scaler.scale(loss).backward()
        scaler.unscale_(opt)
        if double_unscale:
            scaler.unscale_(opt)  # must NOT divide by the scale again
        g = {p.name: np.asarray(p._grad._data) for p in net.parameters()}
        scaler.step(opt)  # internal unscale_ is also a no-op now
        scaler.update()
        return g

    g1 = one_step(double_unscale=False)
    g2 = one_step(double_unscale=True)
    # same weights moved identically => second step's grads are the honest
    # once-unscaled grads of the updated net, not double-divided
    assert all(np.isfinite(v).all() for v in g2.values())
    for k in g1:
        assert not np.allclose(g2[k], g1[k] / 2.0 ** 8)
    # update() re-arms: the next step unscales exactly once again
    g3 = one_step(double_unscale=False)
    assert all(np.abs(v).max() < 1e3 for v in g3.values())


def test_gradscaler_rearms_on_next_scale_without_update():
    """Loops that call unscale_ + optimizer.step() directly (no
    scaler.step()/update()) must still unscale once EVERY iteration: the
    next scale() opens a new step."""
    from paddle_tpu.amp import GradScaler
    paddle.seed(6)
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(0.01, parameters=net.parameters())
    scaler = GradScaler(init_loss_scaling=2.0 ** 10)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    grads = []
    for _ in range(2):
        opt.clear_grad()
        out = net(x)
        loss = (out * out).mean()
        scaler.scale(loss).backward()
        scaler.unscale_(opt)
        grads.append({p.name: np.asarray(p._grad._data)
                      for p in net.parameters()})
        opt.step()  # no scaler.update(): iteration 2 must still unscale
    for k in grads[1]:
        assert np.abs(grads[1][k]).max() < 1e3, k  # not scale-inflated


# ---------------------------------------------------------------------------
# DataLoader: timeout + position state
# ---------------------------------------------------------------------------


class _StuckDataset:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 4:
            time.sleep(30)
        return np.zeros(2, np.float32)


def test_dataloader_timeout_raises_on_stuck_worker():
    dl = DataLoader(_StuckDataset(), batch_size=2, num_workers=1,
                    timeout=0.3)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="timeout"):
        for _ in dl:
            pass
    assert time.monotonic() - t0 < 10.0


def test_dataloader_timeout_zero_still_waits():
    class Slow:
        def __len__(self):
            return 2

        def __getitem__(self, i):
            time.sleep(0.2)
            return np.zeros(2, np.float32)

    dl = DataLoader(Slow(), batch_size=1, num_workers=1, timeout=0)
    assert len(list(dl)) == 2
    with pytest.raises(ValueError, match="timeout"):
        DataLoader(Slow(), batch_size=1, timeout=-1)


def test_dataloader_position_state_skips_without_fetching():
    fetched = []

    class Tracking:
        def __len__(self):
            return 12

        def __getitem__(self, i):
            fetched.append(i)
            return np.float32(i)

    dl = DataLoader(Tracking(), batch_size=2)
    seen = []
    for i, b in enumerate(dl):
        seen.append(np.asarray(b._data).tolist())
        if i == 2:
            st = dl.state_dict()
            # position recorded in GLOBAL-SAMPLE terms (topology-elastic
            # resume) alongside the raw batch count
            assert st == {"batches_served": 3, "samples_served": 6,
                          "batch_size": 2}
    fetched.clear()
    dl2 = DataLoader(Tracking(), batch_size=2)
    dl2.load_state_dict(st)
    rest = [np.asarray(b._data).tolist() for b in dl2]
    assert rest == seen[3:]
    assert min(fetched) >= 6  # skipped prefix fetched nothing
    # the skip is one-shot: the next epoch starts from the top
    assert len(list(dl2)) == 6


def test_dataloader_position_state_iterable_dataset():
    from paddle_tpu.io import IterableDataset

    class Stream(IterableDataset):
        def __iter__(self):
            return iter(np.arange(10, dtype=np.float32))

    dl = DataLoader(Stream(), batch_size=2)
    dl.load_state_dict({"batches_served": 3})
    got = [np.asarray(b._data).tolist() for b in dl]
    assert got == [[6.0, 7.0], [8.0, 9.0]]


# ---------------------------------------------------------------------------
# elastic seed classes (previously untested semantics)
# ---------------------------------------------------------------------------


def test_elastic_agent_max_restarts_boundary(tmp_path):
    """Exactly max_restarts failures then success -> run() completes and
    the budget is fully spent; one more failure would give up."""
    mgr = CheckpointManager(tmp_path, async_save=False)
    calls = {"n": 0}

    def flaky(state, start_step):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError(f"boom {calls['n']}")
        return "done"

    agent = elastic.ElasticAgent(flaky, mgr, max_restarts=2)
    assert agent.run() == "done"
    assert agent.restarts == 2 and calls["n"] == 3


def test_elastic_agent_preemption_is_not_a_restart(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False)

    def preempted(state, start_step):
        raise fi.Preemption("scheduler said goodbye")

    agent = elastic.ElasticAgent(preempted, mgr, max_restarts=5)
    with pytest.raises(fi.Preemption):
        agent.run()
    assert agent.restarts == 0  # budget untouched: exit, don't retrain


def test_elastic_agent_falls_back_past_corrupt_checkpoint(tmp_path):
    mgr = CheckpointManager(tmp_path, async_save=False)
    mgr.save(3, {"v": np.float64(3.0)})
    mgr.save(6, {"v": np.float64(6.0)})
    (tmp_path / "step_6" / "state.pdckpt").write_bytes(b"rot")
    crashed = {"done": False}

    def train_fn(state, start_step):
        if not crashed["done"]:
            crashed["done"] = True
            raise RuntimeError("die once")
        return float(state["v"]), start_step

    agent = elastic.ElasticAgent(train_fn, mgr, max_restarts=1)
    v, start = agent.run()
    assert (v, start) == (3.0, 3)  # step 6 quarantined, step 3 adopted


def test_elastic_agent_start_step_matches_loaded_state(tmp_path,
                                                       monkeypatch):
    """When restore falls back past an unreadable (not corrupt) newest
    step, the agent's start_step must be the step it ACTUALLY loaded —
    not latest_step(), which still lists the unreadable one."""
    from paddle_tpu.incubate import checkpoint as ckpt_mod
    mgr = CheckpointManager(tmp_path, async_save=False, retries=0)
    mgr.save(5, {"v": np.float64(5.0)})
    mgr.save(7, {"v": np.float64(7.0)})
    real_load = ckpt_mod.fio.load

    def load(path, **kw):
        if "step_7" in path:
            raise OSError("EIO")
        return real_load(path, **kw)

    monkeypatch.setattr(ckpt_mod.fio, "load", load)
    seen = []

    def train_fn(state, start_step):
        seen.append((float(state["v"]), start_step))
        return "ok"

    assert elastic.ElasticAgent(train_fn, mgr).run() == "ok"
    assert seen == [(5.0, 5)]  # state and step agree
    assert mgr.all_steps() == [5, 7]  # step 7 kept on disk, not quarantined


def test_nanguard_every_n_cadence():
    guard = elastic.NanGuard(every_n_steps=3)
    guard(np.array([np.nan]))  # steps 1,2 unchecked
    guard(np.array([np.nan]))
    with pytest.raises(elastic.NonFiniteError):
        guard(np.array([np.nan]))  # step 3 checked
    guard(np.array([1.0]))  # 4
    guard(np.array([np.inf]))  # 5
    with pytest.raises(elastic.NonFiniteError):
        guard(np.array([np.inf]))  # 6 checked


def test_heartbeat_monitor_stale_and_missing(tmp_path):
    import json
    # rank 0: stale beat (frozen clock), rank 1: missing file entirely
    with open(tmp_path / "hb_0.json", "w") as f:
        json.dump({"ts": time.time() - 60.0, "rank": 0, "step": 5,
                   "status": "running"}, f)
    mon = elastic.HeartbeatMonitor(tmp_path, world_size=2, timeout=1.0)
    assert mon.failed_ranks() == [0, 1]
    info = mon.poll()
    assert info[0]["age"] > 50 and info[1] is None
    # fresh beat clears rank 0
    elastic.Heartbeat(tmp_path, rank=0).beat(step=6)
    assert mon.failed_ranks() == [1]


def test_all_finite_traceable():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return elastic.all_finite({"a": x, "b": jnp.ones(3),
                                   "n": jnp.arange(3)})

    assert bool(f(jnp.ones(4)))
    assert not bool(f(jnp.array([1.0, jnp.nan, 0.0, 2.0])))


# ---------------------------------------------------------------------------
# hapi Model.fit: checkpointed fit with mid-epoch exact resume
# ---------------------------------------------------------------------------


def _fit_model(seed):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    model = paddle.Model(net)
    opt = paddle.optimizer.AdamW(0.01, parameters=net.parameters())
    model.prepare(optimizer=opt, loss=nn.MSELoss(), jit=True)
    return model


def _fit_dataset():
    rng = np.random.default_rng(0)
    from paddle_tpu.io import TensorDataset
    return TensorDataset([
        paddle.to_tensor(rng.standard_normal((24, 8)).astype(np.float32)),
        paddle.to_tensor(rng.standard_normal((24, 2)).astype(np.float32))])


def test_fit_preempt_and_resume_bitwise_mid_epoch(tmp_path):
    ds = _fit_dataset()
    m1 = _fit_model(11)
    m1.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0)
    golden = {n: np.asarray(p._data) for n, p in m1.network.named_parameters()}

    # ckpt_freq=5 lands the last save MID epoch 1 (batch 5 of 6); preempt
    # during epoch 2
    m2 = _fit_model(11)
    with pytest.raises(fi.Preemption):
        with fi.inject(fi.FaultPlan(preempt_at_step=8)):
            m2.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0,
                   ckpt_dir=tmp_path, ckpt_freq=5)

    m3 = _fit_model(11)  # fresh "process": different live weights until load
    m3.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0,
           ckpt_dir=tmp_path, ckpt_freq=5, resume=True)
    resumed = {n: np.asarray(p._data)
               for n, p in m3.network.named_parameters()}
    for n in golden:
        np.testing.assert_array_equal(golden[n], resumed[n]), n


def test_fit_sigterm_deferred_flush_and_resume_bitwise(tmp_path):
    """SIGTERM during fit defers to the next batch boundary: the handler
    only marks preempted, the loop flushes a CONSISTENT snapshot (weights,
    RNG, position from the same boundary) and raises Preempted; the resumed
    run stays bitwise on the golden trajectory."""
    from paddle_tpu.incubate.checkpoint import Preempted
    ds = _fit_dataset()
    m1 = _fit_model(13)
    m1.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0)
    golden = {n: np.asarray(p._data) for n, p in m1.network.named_parameters()}

    m2 = _fit_model(13)
    fired = {"n": 0}

    class Arm:  # raise SIGTERM from a callback: lands mid-loop like a real one
        def on_train_batch_end(self, *a, **k):
            fired["n"] += 1
            if fired["n"] == 7:
                signal.raise_signal(signal.SIGTERM)

        def __getattr__(self, name):
            return lambda *a, **k: None

    with pytest.raises(Preempted, match="flushed"):
        m2.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0,
               ckpt_dir=tmp_path, callbacks=[Arm()])
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL  # hook removed

    m3 = _fit_model(13)
    m3.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0,
           ckpt_dir=tmp_path, resume=True)
    resumed = {n: np.asarray(p._data)
               for n, p in m3.network.named_parameters()}
    for n in golden:
        np.testing.assert_array_equal(golden[n], resumed[n]), n


def test_fit_resume_from_epoch_final_save_rolls_to_next_epoch(tmp_path):
    """A checkpoint taken at the last batch of an epoch resumes INTO the
    next epoch — no empty-epoch replay re-firing on_epoch_end/eval."""
    ds = _fit_dataset()  # 24 samples / batch 4 = 6 batches per epoch
    m1 = _fit_model(17)
    m1.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0)
    golden = {n: np.asarray(p._data) for n, p in m1.network.named_parameters()}

    m2 = _fit_model(17)
    with pytest.raises(fi.Preemption):
        with fi.inject(fi.FaultPlan(preempt_at_step=8)):
            # ckpt_freq=6 == epoch length: last save is the epoch-1 final
            m2.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0,
                   ckpt_dir=tmp_path, ckpt_freq=6)
    epoch_ends = []

    class Spy:
        def on_epoch_end(self, epoch, logs=None):
            epoch_ends.append((epoch, logs))

        def __getattr__(self, name):
            return lambda *a, **k: None

    m3 = _fit_model(17)
    m3.fit(ds, batch_size=4, epochs=2, shuffle=True, verbose=0,
           ckpt_dir=tmp_path, ckpt_freq=6, resume=True, callbacks=[Spy()])
    assert [e for e, _ in epoch_ends] == [1]  # epoch 0 NOT replayed empty
    assert epoch_ends[0][1].get("loss") is not None
    resumed = {n: np.asarray(p._data)
               for n, p in m3.network.named_parameters()}
    for n in golden:
        np.testing.assert_array_equal(golden[n], resumed[n]), n


def test_fit_resume_requires_positional_loader(tmp_path):
    m = _fit_model(1)
    gen = iter([])
    with pytest.raises(ValueError, match="resume"):
        m.fit(gen, epochs=1, verbose=0, ckpt_dir=tmp_path, resume=True)
