"""Pipeline-parallel schedules: GPipe vs 1F1B parity + memory.

Models the reference's pipeline tests (ref: test/collective/fleet
hybrid_parallel_pp_*.py) — forward/backward parity against a sequential
run, and the 1F1B activation-residency claim (O(S) vs O(M)) checked via
XLA's compiled memory analysis.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.distributed import pipeline as pl


def _block(lp, h):
    return jnp.tanh(h @ lp["w"] + lp["b"])


def _setup(S=4, L_per=2, B=16, F=32, seed=0):
    L = S * L_per
    params = {
        "w": jax.random.normal(jax.random.PRNGKey(seed), (L, F, F)) * 0.3,
        "b": jnp.zeros((L, F)),
    }
    x = jax.random.normal(jax.random.PRNGKey(seed + 1), (B, F))
    mesh = Mesh(np.array(jax.devices()[:S]).reshape(S), ("pp",))
    return params, x, mesh, L


def _loss_fn(schedule, mesh, M):
    def loss(p, x):
        out = pl.run_pipeline(_block, p, x, M, mesh=mesh, schedule=schedule)
        return jnp.sum(out ** 2)
    return loss


def _loss_seq(L):
    def loss(p, x):
        h = x
        for i in range(L):
            h = _block({"w": p["w"][i], "b": p["b"][i]}, h)
        return jnp.sum(h ** 2)
    return loss


class TestPipelineSchedules:
    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_forward_backward_parity(self, devices8, schedule):
        params, x, mesh, L = _setup()
        M = 8
        with mesh:
            l_ref, g_ref = jax.value_and_grad(_loss_seq(L))(params, x)
            l_pp, g_pp = jax.jit(
                jax.value_and_grad(_loss_fn(schedule, mesh, M)))(params, x)
        assert np.allclose(float(l_ref), float(l_pp), rtol=1e-5)
        for k in g_ref:
            np.testing.assert_allclose(np.asarray(g_ref[k]),
                                       np.asarray(g_pp[k]), rtol=1e-4,
                                       atol=1e-5)

    def test_1f1b_input_grads(self, devices8):
        params, x, mesh, L = _setup()
        with mesh:
            gx_ref = jax.grad(_loss_seq(L), argnums=1)(params, x)
            gx_pp = jax.jit(
                jax.grad(_loss_fn("1f1b", mesh, 8), argnums=1))(params, x)
        np.testing.assert_allclose(np.asarray(gx_ref), np.asarray(gx_pp),
                                   rtol=1e-4, atol=1e-5)

    def test_1f1b_microbatch_counts(self, devices8):
        """Schedule correctness across M (including M < S and M == 1)."""
        params, x, mesh, L = _setup(B=24)
        for M in (1, 2, 4, 12, 24):
            with mesh:
                l_ref = _loss_seq(L)(params, x)
                l_pp = jax.jit(_loss_fn("1f1b", mesh, M))(params, x)
            assert np.allclose(float(l_ref), float(l_pp), rtol=1e-5), M

    @pytest.mark.parametrize("S,V,L_per", [(4, 2, 1), (2, 3, 2)])
    def test_interleaved_1f1b_parity(self, devices8, S, V, L_per):
        """Virtual-pipeline (interleaved) schedule == sequential reference
        (ref: pipeline_parallel.py:613 interleaved 1F1B)."""
        L = S * V * L_per
        F, B, M = 32, 12, 6
        params = {
            "w": jax.random.normal(jax.random.PRNGKey(0), (L, F, F)) * 0.3,
            "b": jnp.zeros((L, F)),
        }
        x = jax.random.normal(jax.random.PRNGKey(1), (B, F))
        mesh = Mesh(np.array(jax.devices()[:S]).reshape(S), ("pp",))

        def loss_il(p, x):
            out = pl.run_pipeline(_block, p, x, M, mesh=mesh,
                                  schedule="1f1b", interleave=V)
            return jnp.sum(out ** 2)

        with mesh:
            l_ref, g_ref = jax.value_and_grad(_loss_seq(L))(params, x)
            l_il, g_il = jax.jit(jax.value_and_grad(loss_il))(params, x)
        assert np.allclose(float(l_ref), float(l_il), rtol=1e-5)
        for k in g_ref:
            np.testing.assert_allclose(np.asarray(g_ref[k]),
                                       np.asarray(g_il[k]), rtol=1e-4,
                                       atol=1e-5)

    def test_1f1b_activation_residency_lower(self, devices8):
        """1F1B's backward stashes at most 2S-1 microbatch inputs; GPipe's
        autodiff saves residuals for all M+S-1 ticks. With M >> S the
        compiled temp memory must be strictly smaller."""
        params, x, mesh, L = _setup(L_per=4, B=64, F=128)
        M = 32
        temps = {}
        with mesh:
            for sched in ("gpipe", "1f1b"):
                c = jax.jit(jax.value_and_grad(
                    _loss_fn(sched, mesh, M))).lower(params, x).compile()
                temps[sched] = c.memory_analysis().temp_size_in_bytes
        assert temps["1f1b"] < temps["gpipe"], temps
