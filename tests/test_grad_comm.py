"""Explicit gradient-communication layer (distributed/grad_comm.py) on the
8-virtual-device CPU mesh: reduce-scatter + sharded-update + all-gather
parity with the all-reduce baseline (bitwise in fp32), quantized bf16/int8
reduce tolerances, bucketing invariance, comm counters, and the satellite
fixes (ReduceOp.PROD, stage-3 divisibility fallback)."""
import logging

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import env as dist_env
from paddle_tpu.distributed import grad_comm


_DEFAULT_FLAGS = {
    "FLAGS_grad_comm": "auto",
    "FLAGS_weight_update_sharding": False,
    "FLAGS_allreduce_dtype": "float32",
    "FLAGS_grad_bucket_bytes": 16 * 2 ** 20,
}

AR = {"FLAGS_grad_comm": "on", "FLAGS_weight_update_sharding": False}
RS = {"FLAGS_grad_comm": "on", "FLAGS_weight_update_sharding": True}


@pytest.fixture(autouse=True)
def _reset_flags(devices8):
    yield
    paddle.set_flags(dict(_DEFAULT_FLAGS))
    dist_env.set_mesh(None)


def _model(width=64, seed=7):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(width, width), nn.ReLU(),
                         nn.Linear(width, 8))


def _batch(n=16, width=64, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((n, width)).astype(np.float32),
            rng.standard_normal((n, 8)).astype(np.float32))


def _train(flags, steps=3, k=1, opt_cls=None, seed=7, clip=None, lr=0.01):
    paddle.set_flags(dict(_DEFAULT_FLAGS))
    paddle.set_flags(flags)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    m = _model(seed=seed)
    opt_cls = opt_cls or paddle.optimizer.AdamW
    opt = opt_cls(lr, parameters=m.parameters(), grad_clip=clip)
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt, mesh=mesh,
                                accumulate_steps=k)
    x, y = _batch()
    losses = [float(step(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
              for _ in range(steps)]
    return {n: np.asarray(a) for n, a in step.params.items()}, losses, step


# ---------------------------------------------------------------------------
# (a) fp32 parity: rs/ag + sharded update == all-reduce + replicated update


def test_rs_ag_bitwise_parity_with_allreduce():
    p_ar, _, _ = _train(AR)
    p_rs, _, _ = _train(RS)
    for n in p_ar:
        np.testing.assert_array_equal(p_ar[n], p_rs[n]), n


def test_explicit_paths_match_default_gspmd_schedule():
    p_def, _, step = _train({})
    assert step._gc_cfg is None  # flags off -> default path untouched
    p_ar, _, step_ar = _train(AR)
    assert step_ar._gc_cfg is not None
    for n in p_def:
        np.testing.assert_allclose(p_def[n], p_ar[n], rtol=1e-5, atol=1e-6)


def test_sharded_update_state_is_packed_and_dp_sharded():
    _, _, step = _train(RS)
    for name, sl in step.opt_state["slots"].items():
        for k, arr in sl.items():
            assert arr.ndim == 2 and arr.shape[0] == 8, (name, k)
            assert arr.sharding.spec[0] == "dp", (name, k)
    # params leave the step replicated (full) on every device
    for n, p in step.params.items():
        assert all(s is None for s in (p.sharding.spec or [None]))


def test_grad_clip_global_norm_parity():
    clip = paddle.nn.ClipGradByGlobalNorm(0.05)
    p_ar, _, _ = _train(AR, clip=clip)
    p_rs, _, _ = _train(RS, clip=clip)
    p_def, _, _ = _train({}, clip=clip)
    for n in p_ar:
        np.testing.assert_array_equal(p_ar[n], p_rs[n])
        np.testing.assert_allclose(p_def[n], p_rs[n], rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# (b) quantized reduce: tolerance + loss-curve sanity over 20 steps


def test_bf16_quantized_reduce_tolerance_and_loss_sanity():
    p_rs, _, _ = _train(RS, steps=20)
    p_bf, losses, _ = _train(dict(RS, FLAGS_allreduce_dtype="bfloat16"),
                             steps=20)
    for n in p_rs:
        np.testing.assert_allclose(p_rs[n], p_bf[n], rtol=0.05, atol=0.02)
    assert losses[-1] < losses[0] * 0.9, losses


def test_int8_quantized_reduce_tolerance_and_loss_sanity():
    p_rs, _, _ = _train(RS, steps=20)
    p_i8, losses, _ = _train(dict(RS, FLAGS_allreduce_dtype="int8"), steps=20)
    for n in p_rs:
        np.testing.assert_allclose(p_rs[n], p_i8[n], rtol=0.3, atol=0.12)
    assert losses[-1] < losses[0] * 0.9, losses


# ---------------------------------------------------------------------------
# (c) bucketing invariance


def test_bucketing_invariant_under_bucket_bytes():
    p_big, _, step_big = _train(RS)
    p_small, _, step_small = _train(dict(RS, FLAGS_grad_bucket_bytes=4096))
    for n in p_big:
        np.testing.assert_array_equal(p_big[n], p_small[n])
    assert len(step_small._gc_cfg.plan.buckets) > \
        len(step_big._gc_cfg.plan.buckets)


# ---------------------------------------------------------------------------
# comm counters (tier-1 gate: rs/ag must emit fewer reduce bytes)


def test_rs_emits_fewer_reduce_bytes_than_allreduce():
    import paddle_tpu.profiler as profiler
    profiler.reset_comm_counters()
    _train(AR, steps=1)
    ar = profiler.comm_counters()
    profiler.reset_comm_counters()
    _train(RS, steps=1)
    rs = profiler.comm_counters()
    assert ar["reduce_bytes"] > 0 and rs["reduce_bytes"] > 0
    # ring all-reduce = RS + AG: exactly 2x the reduce-scatter wire bytes
    assert rs["reduce_bytes"] * 2 == ar["reduce_bytes"]
    assert rs["gather_bytes"] > 0
    assert rs["buckets"] >= 1 and 0 < rs["bucket_fill"] <= 1.0


def test_quantized_reduce_bytes_halve_again():
    import paddle_tpu.profiler as profiler
    profiler.reset_comm_counters()
    _train(RS, steps=1)
    f32 = profiler.comm_counters()
    profiler.reset_comm_counters()
    _train(dict(RS, FLAGS_allreduce_dtype="bfloat16"), steps=1)
    bf = profiler.comm_counters()
    assert bf["reduce_bytes"] * 2 == f32["reduce_bytes"]
    assert "bfloat16" in bf["reduce_bytes_by_dtype"]
    profiler.reset_comm_counters()
    _train(dict(RS, FLAGS_allreduce_dtype="int8"), steps=1)
    i8 = profiler.comm_counters()
    # int8 payload is 1/4 of fp32 (+ small fp32 per-chunk scales)
    assert i8["reduce_bytes"] < f32["reduce_bytes"] // 2


# ---------------------------------------------------------------------------
# gradient accumulation: per-micro-step reduce-scatter into sharded accum


def test_accumulation_parity_and_sharded_accumulator():
    p_ar, _, _ = _train(AR, steps=8, k=4)
    p_rs, _, step = _train(RS, steps=8, k=4)
    p_def, _, _ = _train({}, steps=8, k=4)
    for n in p_ar:
        np.testing.assert_array_equal(p_ar[n], p_rs[n])
        np.testing.assert_allclose(p_def[n], p_rs[n], rtol=1e-5, atol=1e-6)
    acc = next(iter(step._grad_accum.values()))
    assert acc.shape[0] == 8 and acc.sharding.spec[0] == "dp"
    assert isinstance(step._jitted, dict)  # micro/fire program pair


def test_accumulation_micro_steps_record_reduce_only():
    import paddle_tpu.profiler as profiler
    paddle.set_flags(dict(_DEFAULT_FLAGS))
    paddle.set_flags(RS)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    m = _model()
    opt = paddle.optimizer.SGD(0.1, parameters=m.parameters())
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt, mesh=mesh,
                                accumulate_steps=2)
    x, y = _batch()
    profiler.reset_comm_counters()
    step(paddle.to_tensor(x), paddle.to_tensor(y))   # micro: RS only
    micro = profiler.comm_counters()
    assert micro["gather_bytes"] == 0 and micro["reduce_bytes"] > 0
    step(paddle.to_tensor(x), paddle.to_tensor(y))   # fire: RS + param AG
    fire = profiler.comm_counters()
    assert fire["gather_bytes"] > 0


def test_checkpoint_roundtrip_packed_layout():
    _, _, step = _train(RS, steps=1, k=2)  # mid-accumulation
    snap = step.state_for_checkpoint()
    assert snap["micro"] == 1
    x, y = _batch()
    step(paddle.to_tensor(x), paddle.to_tensor(y))
    after = {n: np.asarray(a) for n, a in step.params.items()}
    step.restore_from_checkpoint(snap)
    step(paddle.to_tensor(x), paddle.to_tensor(y))
    for n in after:
        np.testing.assert_allclose(after[n], np.asarray(step.params[n]),
                                   rtol=1e-6)


# ---------------------------------------------------------------------------
# fallbacks


def test_restore_packed_checkpoint_into_fresh_trainstep():
    """A checkpoint saved under weight-update sharding (packed slots) must
    restore into a NEW TrainStep before its first compile — resolve()
    accepts the packed slot layout and pack_opt_state passes it through."""
    _, _, step = _train(RS, steps=2)
    snap = step.state_for_checkpoint()
    x, y = _batch()
    step(paddle.to_tensor(x), paddle.to_tensor(y))
    after = {n: np.asarray(a) for n, a in step.params.items()}

    paddle.set_flags(dict(_DEFAULT_FLAGS))
    paddle.set_flags(RS)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    m2 = _model(seed=7)
    opt2 = paddle.optimizer.AdamW(0.01, parameters=m2.parameters())
    fresh = paddle.jit.TrainStep(m2, nn.MSELoss(), opt2, mesh=mesh)
    fresh.restore_from_checkpoint(snap)   # before first call/compile
    fresh(paddle.to_tensor(x), paddle.to_tensor(y))
    assert fresh._gc_cfg is not None and \
        fresh._gc_cfg.weight_update_sharding
    for n in after:
        np.testing.assert_allclose(after[n], np.asarray(fresh.params[n]),
                                   rtol=1e-6)


def test_restore_packed_checkpoint_after_flag_off_compile():
    """Cross-layout restore AFTER the step compiled: a packed checkpoint
    restored into an already-built replicated-schedule step must unpack."""
    _, _, step = _train(RS, steps=2)
    snap = step.state_for_checkpoint()
    p_src = {n: np.asarray(a) for n, a in step.params.items()}

    paddle.set_flags(dict(_DEFAULT_FLAGS))
    dist_env.set_mesh(None)
    m2 = _model(seed=7)
    opt2 = paddle.optimizer.AdamW(0.01, parameters=m2.parameters())
    plain = paddle.jit.TrainStep(m2, nn.MSELoss(), opt2)
    x, y = _batch()
    plain(paddle.to_tensor(x), paddle.to_tensor(y))   # compile first
    plain.restore_from_checkpoint(snap)               # then restore packed
    plain(paddle.to_tensor(x), paddle.to_tensor(y))   # must not crash
    for n in p_src:
        assert not np.array_equal(p_src[n], np.asarray(plain.params[n]))


def test_restore_packed_checkpoint_with_flags_off():
    """A weight-update-sharding checkpoint restored into a default-schedule
    step (flags off, or no mesh) must unpack its (n, cols) slots back to
    param shapes instead of crashing the fused update."""
    _, _, step = _train(RS, steps=2)
    snap = step.state_for_checkpoint()
    paddle.set_flags(dict(_DEFAULT_FLAGS))
    dist_env.set_mesh(None)
    m2 = _model(seed=7)
    opt2 = paddle.optimizer.AdamW(0.01, parameters=m2.parameters())
    fresh = paddle.jit.TrainStep(m2, nn.MSELoss(), opt2)  # no mesh at all
    fresh.restore_from_checkpoint(snap)
    x, y = _batch()
    fresh(paddle.to_tensor(x), paddle.to_tensor(y))
    assert fresh._gc_cfg is None
    for name, sl in fresh.opt_state["slots"].items():
        for k, arr in sl.items():
            assert tuple(arr.shape) == tuple(fresh.params[name].shape)


def test_quantized_reduce_works_for_non_elementwise_optimizer():
    """Wire compression alone (no weight-update sharding) updates full
    params and must stay active for optimizers like Lamb that cannot take
    the shard-local update."""
    p, _, step = _train({"FLAGS_allreduce_dtype": "bfloat16"},
                        opt_cls=paddle.optimizer.Lamb, lr=0.001)
    assert step._gc_cfg is not None and \
        not step._gc_cfg.weight_update_sharding
    for n, a in p.items():
        assert np.isfinite(a).all()


def test_stage3_sharded_params_fall_back_to_gspmd():
    """ZeRO stage-3 partitions params over the axis; the explicit step would
    replicate them, so grad_comm must decline and keep GSPMD's schedule."""
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    paddle.set_flags(dict(_DEFAULT_FLAGS))
    paddle.set_flags(RS)
    mesh = dist_env.create_hybrid_mesh(sharding=8)
    m = _model()
    opt = paddle.optimizer.AdamW(0.01, parameters=m.parameters())
    m, opt, _ = group_sharded_parallel(m, opt, level="p_g_os")
    step = paddle.jit.TrainStep(m, nn.MSELoss(), opt, mesh=mesh)
    x, y = _batch(8)
    step(paddle.to_tensor(x), paddle.to_tensor(y))
    assert step._gc_cfg is None
    # params still genuinely sharded after the step
    sharded = [n for n, p in step.params.items()
               if p.sharding.spec and any(s == "sharding"
                                          for s in p.sharding.spec)]
    assert sharded


def test_non_elementwise_optimizer_falls_back():
    p_rs, _, step = _train(RS, opt_cls=paddle.optimizer.Lamb, lr=0.001)
    assert step._gc_cfg is None  # Lamb trust ratio is a whole-tensor norm
    for n, a in p_rs.items():
        assert np.isfinite(a).all()


# ---------------------------------------------------------------------------
# satellite: ReduceOp.PROD sign-and-magnitude lowering


def test_allreduce_prod_zero_and_negative_inputs():
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    import paddle_tpu.distributed.collective as coll
    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def f(x):
        out = coll.all_reduce(x, op=coll.ReduceOp.PROD, group="dp")
        return out._data if hasattr(out, "_data") else out

    g = jax.jit(shard_map(f, mesh=mesh, in_specs=P("dp"),
                          out_specs=P("dp"), check_rep=False))
    with_zero = np.array([2.0, -3.0, 0.0, 1.5, -1.0, 4.0, 0.5, -2.0],
                         np.float32)
    no_zero = np.array([2.0, -3.0, 1.0, 1.5, -1.0, 4.0, 0.5, -2.0],
                       np.float32)
    for v in (with_zero, no_zero):
        out = np.asarray(g(v))
        assert np.isfinite(out).all()
        np.testing.assert_allclose(out, np.full(8, np.prod(v)), rtol=1e-5)


# ---------------------------------------------------------------------------
# satellite: stage-3 largest-divisible-dim fallback


def test_stage3_falls_back_to_largest_divisible_dim(caplog):
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from jax.sharding import PartitionSpec as P
    dist_env.create_hybrid_mesh(sharding=8)
    paddle.seed(0)

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            # weight (9, 8): largest dim 9 indivisible by 8 — the seed
            # silently skipped this param; now dim 1 (8) shards
            self.a = nn.Linear(9, 8)
            self.b = nn.Linear(7, 3)     # weight (7, 3): nothing divisible

    m = M()
    opt = paddle.optimizer.AdamW(0.01, parameters=m.parameters())
    with caplog.at_level(logging.WARNING,
                         logger="paddle_tpu.distributed.sharding"):
        group_sharded_parallel(m, opt, level="p_g_os")
    assert m.a.weight.dist_spec == P(None, "sharding")
    assert getattr(m.b.weight, "dist_spec", None) is None
    skip_logs = [r for r in caplog.records if "stay" in r.getMessage()]
    assert len(skip_logs) == 1  # skipped params logged once, not per-param
    assert "b.weight" in skip_logs[0].getMessage()
