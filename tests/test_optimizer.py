"""Optimizer parity vs hand-rolled numpy; LR schedulers; grad clip (ref test/legacy_test/test_*_op)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def quad_setup():
    w = paddle.to_tensor(np.array([1.0, -2.0, 3.0], dtype=np.float32), stop_gradient=False)
    w0 = w.numpy().copy()
    return w, w0


class TestSGD:
    def test_step_parity(self):
        w, w0 = quad_setup()
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        (w * w).sum().backward()
        opt.step()
        np.testing.assert_allclose(w.numpy(), w0 - 0.1 * 2 * w0, rtol=1e-5)

    def test_momentum(self):
        w, w0 = quad_setup()
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9, parameters=[w])
        v = np.zeros_like(w0)
        cur = w0.copy()
        for _ in range(3):
            opt.clear_grad()
            (w * w).sum().backward()
            opt.step()
            g = 2 * cur
            v = 0.9 * v + g
            cur = cur - 0.1 * v
        np.testing.assert_allclose(w.numpy(), cur, rtol=1e-4)


class TestAdam:
    def test_adam_parity(self):
        w, w0 = quad_setup()
        lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
        opt = paddle.optimizer.Adam(learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps, parameters=[w])
        m = np.zeros_like(w0)
        v = np.zeros_like(w0)
        cur = w0.copy()
        for t in range(1, 4):
            opt.clear_grad()
            (w * w).sum().backward()
            opt.step()
            g = 2 * cur
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mh, vh = m / (1 - b1**t), v / (1 - b2**t)
            cur = cur - lr * mh / (np.sqrt(vh) + eps)
        np.testing.assert_allclose(w.numpy(), cur, rtol=1e-4, atol=1e-6)

    def test_adamw_decoupled_decay(self):
        w, w0 = quad_setup()
        opt = paddle.optimizer.AdamW(learning_rate=0.01, weight_decay=0.1, parameters=[w])
        opt.clear_grad()
        (w * w).sum().backward()
        opt.step()
        # decoupled: w -= lr*wd*w in addition to adam step
        assert not np.allclose(w.numpy(), w0)

    def test_rmsprop_adagrad_run(self):
        for cls in [paddle.optimizer.RMSProp, paddle.optimizer.Adagrad,
                    paddle.optimizer.Adadelta, paddle.optimizer.Adamax,
                    paddle.optimizer.Lamb]:
            w, w0 = quad_setup()
            kw = {}
            opt = cls(learning_rate=0.01, parameters=[w], **kw)
            opt.clear_grad()
            (w * w).sum().backward()
            opt.step()
            assert np.isfinite(w.numpy()).all()
            assert not np.allclose(w.numpy(), w0)


class TestTraining:
    def test_linear_regression_converges(self):
        rng = np.random.RandomState(0)
        X = rng.randn(64, 3).astype(np.float32)
        true_w = np.array([[1.0], [-2.0], [0.5]], dtype=np.float32)
        Y = X @ true_w
        m = nn.Linear(3, 1)
        opt = paddle.optimizer.Adam(learning_rate=0.05, parameters=m.parameters())
        xt, yt = paddle.to_tensor(X), paddle.to_tensor(Y)
        loss0 = None
        for i in range(100):
            opt.clear_grad()
            loss = ((m(xt) - yt) ** 2).mean()
            loss.backward()
            opt.step()
            if i == 0:
                loss0 = float(loss)
        assert float(loss) < 0.05 * loss0


class TestLRSchedulers:
    def test_step_decay(self):
        sch = paddle.optimizer.lr.StepDecay(learning_rate=1.0, step_size=2, gamma=0.5)
        vals = []
        for _ in range(5):
            vals.append(sch.get_lr() if hasattr(sch, "get_lr") else sch())
            sch.step()
        np.testing.assert_allclose(vals, [1.0, 1.0, 0.5, 0.5, 0.25], rtol=1e-6)

    def test_cosine_warmup_piecewise(self):
        c = paddle.optimizer.lr.CosineAnnealingDecay(learning_rate=1.0, T_max=10)
        first = c.get_lr()
        for _ in range(10):
            c.step()
        assert c.get_lr() < first
        w = paddle.optimizer.lr.LinearWarmup(
            paddle.optimizer.lr.PiecewiseDecay([5, 10], [1.0, 0.5, 0.1]),
            warmup_steps=3, start_lr=0.0, end_lr=1.0)
        assert w.get_lr() == 0.0
        w.step()
        assert 0 < w.get_lr() <= 1.0

    def test_noam_onecycle(self):
        n = paddle.optimizer.lr.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
        v0 = n.get_lr()
        n.step()
        assert n.get_lr() != v0 or v0 >= 0
        for cls, kw in [(paddle.optimizer.lr.ExponentialDecay, dict(learning_rate=1.0, gamma=0.9)),
                        (paddle.optimizer.lr.PolynomialDecay, dict(learning_rate=1.0, decay_steps=10)),
                        (paddle.optimizer.lr.MultiStepDecay, dict(learning_rate=1.0, milestones=[2, 4])),
                        (paddle.optimizer.lr.LambdaDecay, dict(learning_rate=1.0, lr_lambda=lambda e: 0.9**e))]:
            s = cls(**kw)
            s.step()
            assert np.isfinite(s.get_lr())

    def test_reduce_on_plateau(self):
        s = paddle.optimizer.lr.ReduceOnPlateau(learning_rate=1.0, factor=0.5, patience=1)
        for loss in [1.0, 1.0, 1.0, 1.0]:
            s.step(loss)
        assert s.get_lr() < 1.0

    def test_scheduler_with_optimizer(self):
        w, _ = quad_setup()
        sch = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.1)
        opt = paddle.optimizer.SGD(learning_rate=sch, parameters=[w])
        (w * w).sum().backward()
        opt.step()
        sch.step()
        opt.clear_grad()
        (w * w).sum().backward()
        opt.step()
        assert np.isfinite(w.numpy()).all()


class TestGradClip:
    def test_global_norm_clip(self):
        w = paddle.to_tensor(np.array([10.0, 10.0], dtype=np.float32), stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[w],
                                   grad_clip=nn.ClipGradByGlobalNorm(1.0))
        (w * w).sum().backward()  # grad = [20, 20], norm ~28.3
        w_before = w.numpy().copy()
        opt.step()
        delta = np.abs(w.numpy() - w_before)
        np.testing.assert_allclose(np.sqrt((delta**2).sum()), 1.0, rtol=1e-4)

    def test_clip_by_value(self):
        w = paddle.to_tensor(np.array([5.0], dtype=np.float32), stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[w],
                                   grad_clip=nn.ClipGradByValue(1.0))
        (w * w).sum().backward()  # grad = 10 -> clipped to 1
        opt.step()
        np.testing.assert_allclose(w.numpy(), [4.0], rtol=1e-5)


class TestMetric:
    def test_accuracy(self):
        m = paddle.metric.Accuracy()
        pred = paddle.to_tensor(np.array([[0.9, 0.1], [0.2, 0.8]], dtype=np.float32))
        label = paddle.to_tensor(np.array([[0], [0]], dtype=np.int64))
        m.update(m.compute(pred, label)) if hasattr(m, "compute") else None
        correct = m.compute(pred, label)
        m.update(correct)
        assert abs(m.accumulate() - 0.5) < 1e-6


class TestMomentDtype:
    """moment_dtype='bfloat16' halves Adam state HBM (the single-chip analog
    of ZeRO moment sharding); update math stays fp32."""

    def test_slots_stored_reduced_functional(self):
        import jax.numpy as jnp
        opt = paddle.optimizer.AdamW(1e-3, moment_dtype="bfloat16")
        params = {"w": jnp.ones((4, 4), jnp.bfloat16)}
        state = opt.init_state(params)
        assert state["slots"]["w"]["moment1"].dtype == jnp.bfloat16
        grads = {"w": jnp.full((4, 4), 0.1, jnp.bfloat16)}
        new_p, new_state = opt.apply_gradients(params, grads, state, 1e-3)
        assert new_state["slots"]["w"]["moment2"].dtype == jnp.bfloat16
        assert new_p["w"].dtype == jnp.bfloat16

    def test_converges_close_to_fp32_moments(self):
        import jax, jax.numpy as jnp
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.normal(size=(64, 8)).astype("float32"))
        yt = X @ jnp.asarray(rng.normal(size=(8, 1)).astype("float32"))
        finals = {}
        for md in ("float32", "bfloat16"):
            opt = paddle.optimizer.Adam(5e-2, moment_dtype=md)
            params = {"w": jnp.zeros((8, 1), jnp.float32)}
            state = opt.init_state(params)
            for _ in range(400):
                loss, g = jax.value_and_grad(
                    lambda p: ((X @ p["w"] - yt) ** 2).mean())(params)
                params, state = opt.apply_gradients(params, g, state, 5e-2)
            finals[md] = float(loss)
        assert finals["bfloat16"] < 1e-2
        assert abs(finals["bfloat16"] - finals["float32"]) < 5e-3
