"""fft / signal / geometric / text / audio / linalg-extras parity tests."""
import numpy as np
import pytest

import paddle_tpu as paddle


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class TestFFT:
    def test_fft_ifft_roundtrip(self):
        x = np.random.RandomState(0).randn(4, 32).astype(np.float32)
        out = _np(paddle.fft.fft(paddle.to_tensor(x)))
        np.testing.assert_allclose(out, np.fft.fft(x), rtol=1e-4, atol=1e-4)
        back = _np(paddle.fft.ifft(paddle.to_tensor(out)))
        np.testing.assert_allclose(back.real, x, rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
    def test_rfft_norms(self, norm):
        x = np.random.RandomState(1).randn(3, 16).astype(np.float64)
        out = _np(paddle.fft.rfft(paddle.to_tensor(x), norm=norm))
        np.testing.assert_allclose(out, np.fft.rfft(x, norm=norm), rtol=1e-10)

    def test_fft2_fftn(self):
        x = np.random.RandomState(2).randn(2, 8, 8)
        np.testing.assert_allclose(_np(paddle.fft.fft2(paddle.to_tensor(x))),
                                   np.fft.fft2(x), rtol=1e-8, atol=1e-8)
        np.testing.assert_allclose(_np(paddle.fft.ifftn(paddle.to_tensor(x))),
                                   np.fft.ifftn(x), rtol=1e-8, atol=1e-8)

    def test_hermitian_family_vs_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.RandomState(3).randn(4, 9) + 1j * np.random.RandomState(4).randn(4, 9)
        for norm in ["backward", "ortho", "forward"]:
            ours = _np(paddle.fft.hfft2(paddle.to_tensor(x), norm=norm))
            ref = torch.fft.hfft2(torch.tensor(x), norm=norm).numpy()
            np.testing.assert_allclose(ours, ref, rtol=1e-8, atol=1e-8)
        r = np.random.RandomState(5).randn(4, 8)
        ours = _np(paddle.fft.ihfft2(paddle.to_tensor(r)))
        ref = torch.fft.ihfft2(torch.tensor(r)).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-8, atol=1e-8)

    def test_freq_shift_helpers(self):
        np.testing.assert_allclose(_np(paddle.fft.fftfreq(8, d=0.5)),
                                   np.fft.fftfreq(8, d=0.5))
        np.testing.assert_allclose(_np(paddle.fft.rfftfreq(8)), np.fft.rfftfreq(8))
        x = np.arange(10.0)
        np.testing.assert_allclose(_np(paddle.fft.fftshift(paddle.to_tensor(x))),
                                   np.fft.fftshift(x))
        np.testing.assert_allclose(_np(paddle.fft.ifftshift(paddle.to_tensor(x))),
                                   np.fft.ifftshift(x))

    def test_fft_grad(self):
        x = paddle.to_tensor(np.random.RandomState(6).randn(8).astype(np.float32))
        x.stop_gradient = False
        y = paddle.fft.rfft(x)
        loss = (y.real() ** 2 + y.imag() ** 2).sum() if hasattr(y, "real") else None
        if loss is None:
            pytest.skip("complex helpers absent")
        loss.backward()
        assert x.grad is not None and np.isfinite(_np(x.grad)).all()


class TestSignal:
    def test_frame_axis_last(self):
        x = np.arange(10.0, dtype=np.float32)
        out = _np(paddle.signal.frame(paddle.to_tensor(x), 4, 2))
        assert out.shape == (4, 4)  # (frame_length, num_frames)
        np.testing.assert_allclose(out[:, 0], x[0:4])
        np.testing.assert_allclose(out[:, 1], x[2:6])

    def test_frame_axis0_and_batch(self):
        x = np.random.RandomState(0).randn(12, 3).astype(np.float32)
        out = _np(paddle.signal.frame(paddle.to_tensor(x), 5, 3, axis=0))
        assert out.shape == (3, 5, 3)
        np.testing.assert_allclose(out[1], x[3:8])

    def test_overlap_add_inverts_frame_nonoverlap(self):
        x = np.random.RandomState(1).randn(2, 12).astype(np.float32)
        f = paddle.signal.frame(paddle.to_tensor(x), 4, 4)
        back = _np(paddle.signal.overlap_add(f, 4))
        np.testing.assert_allclose(back, x, rtol=1e-6)

    def test_stft_vs_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.RandomState(2).randn(2, 256).astype(np.float64)
        win = np.hanning(64).astype(np.float64)  # sym window, len == n_fft
        ours = _np(paddle.signal.stft(paddle.to_tensor(x), n_fft=64,
                                      hop_length=16,
                                      window=paddle.to_tensor(win)))
        ref = torch.stft(torch.tensor(x), n_fft=64, hop_length=16,
                         window=torch.tensor(win), center=True,
                         pad_mode="reflect", onesided=True,
                         return_complex=True).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-8, atol=1e-8)

    def test_istft_roundtrip(self):
        x = np.random.RandomState(3).randn(2, 400).astype(np.float64)
        win = (np.hanning(129)[:-1]).astype(np.float64)  # periodic hann, COLA
        spec = paddle.signal.stft(paddle.to_tensor(x), n_fft=128, hop_length=32,
                                  window=paddle.to_tensor(win))
        back = _np(paddle.signal.istft(spec, n_fft=128, hop_length=32,
                                       window=paddle.to_tensor(win),
                                       length=400))
        np.testing.assert_allclose(back, x, rtol=1e-6, atol=1e-8)


class TestGeometric:
    def test_segment_ops(self):
        data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]],
                                         np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 1, 1]))
        np.testing.assert_allclose(_np(paddle.geometric.segment_sum(data, ids)),
                                   [[4., 6.], [12., 14.]])
        np.testing.assert_allclose(_np(paddle.geometric.segment_mean(data, ids)),
                                   [[2., 3.], [6., 7.]])
        np.testing.assert_allclose(_np(paddle.geometric.segment_min(data, ids)),
                                   [[1., 2.], [5., 6.]])
        np.testing.assert_allclose(_np(paddle.geometric.segment_max(data, ids)),
                                   [[3., 4.], [7., 8.]])

    def test_send_u_recv(self):
        x = paddle.to_tensor(np.array([[0., 2., 3.], [1., 4., 5.], [2., 6., 7.]],
                                      np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0]))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0]))
        out = _np(paddle.geometric.send_u_recv(x, src, dst, reduce_op="sum"))
        np.testing.assert_allclose(out, [[0., 2., 3.], [2., 8., 10.], [1., 4., 5.]])

    def test_send_ue_recv_and_uv(self):
        x = paddle.to_tensor(np.array([[1., 1.], [2., 2.]], np.float32))
        e = paddle.to_tensor(np.array([[1., 0.], [0., 1.], [1., 1.]], np.float32))
        src = paddle.to_tensor(np.array([0, 1, 1]))
        dst = paddle.to_tensor(np.array([1, 0, 0]))
        out = _np(paddle.geometric.send_ue_recv(x, e, src, dst, "add", "sum"))
        np.testing.assert_allclose(out, [[5., 6.], [2., 1.]])
        uv = _np(paddle.geometric.send_uv(x, x, src, dst, "mul"))
        np.testing.assert_allclose(uv, [[2., 2.], [2., 2.], [2., 2.]])

    def test_reindex_and_sample(self):
        x = paddle.to_tensor(np.array([0, 5, 9]))
        neighbors = paddle.to_tensor(np.array([5, 9, 7, 0]))
        count = paddle.to_tensor(np.array([2, 1, 1]))
        src, dst, nodes = paddle.geometric.reindex_graph(x, neighbors, count)
        np.testing.assert_array_equal(_np(nodes), [0, 5, 9, 7])
        np.testing.assert_array_equal(_np(src), [1, 2, 3, 0])
        np.testing.assert_array_equal(_np(dst), [0, 0, 1, 2])
        # CSC graph: col j has rows colptr[j]:colptr[j+1]
        row = paddle.to_tensor(np.array([1, 2, 0, 2, 0, 1]))
        colptr = paddle.to_tensor(np.array([0, 2, 4, 6]))
        nb, cnt = paddle.geometric.sample_neighbors(row, colptr,
                                                    paddle.to_tensor(np.array([0, 2])),
                                                    sample_size=1)
        assert _np(cnt).tolist() == [1, 1]
        assert len(_np(nb)) == 2


class TestText:
    def test_viterbi_matches_bruteforce(self):
        rs = np.random.RandomState(0)
        B, L, C = 3, 5, 4
        pot = rs.rand(B, L, C).astype(np.float32)
        trans = rs.rand(C, C).astype(np.float32)
        lens = np.array([5, 3, 1], np.int64)
        scores, paths = paddle.text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            paddle.to_tensor(lens), include_bos_eos_tag=False)
        scores, paths = _np(scores), _np(paths)
        import itertools
        for b in range(B):
            n = lens[b]
            best, best_path = -1e9, None
            for assign in itertools.product(range(C), repeat=int(n)):
                s = pot[b, 0, assign[0]]
                for t in range(1, n):
                    s += trans[assign[t - 1], assign[t]] + pot[b, t, assign[t]]
                if s > best:
                    best, best_path = s, assign
            np.testing.assert_allclose(scores[b], best, rtol=1e-5)
            np.testing.assert_array_equal(paths[b, :n], best_path)

    def test_viterbi_bos_eos(self):
        rs = np.random.RandomState(1)
        pot = rs.rand(2, 4, 5).astype(np.float32)
        trans = rs.rand(5, 5).astype(np.float32)
        lens = np.array([4, 2], np.int64)
        scores, paths = paddle.text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            paddle.to_tensor(lens), include_bos_eos_tag=True)
        assert _np(scores).shape == (2,) and _np(paths).shape == (2, 4)
        assert np.isfinite(_np(scores)).all()

    def test_datasets_shapes(self):
        ds = paddle.text.Imdb(mode="train")
        doc, label = ds[0]
        assert doc.dtype == np.int64 and label in (0, 1)
        uci = paddle.text.UCIHousing(mode="test")
        x, y = uci[3]
        assert x.shape == (13,) and y.shape == (1,)
        wmt = paddle.text.WMT16(mode="train")
        src, trg, nxt = wmt[5]
        assert trg[0] == 0 and nxt[-1] == 1 and len(trg) == len(nxt)


class TestAudio:
    def test_mel_conversions(self):
        f = paddle.audio.functional.hz_to_mel(440.0)
        back = paddle.audio.functional.mel_to_hz(f)
        assert abs(back - 440.0) < 1e-6
        t = paddle.audio.functional.hz_to_mel(paddle.to_tensor(np.array([440.0])),
                                              htk=True)
        np.testing.assert_allclose(_np(t), 2595.0 * np.log10(1 + 440.0 / 700.0),
                                   rtol=1e-6)

    def test_windows_vs_numpy(self):
        w = _np(paddle.audio.functional.get_window("hann", 16, fftbins=False))
        np.testing.assert_allclose(w, np.hanning(16), atol=1e-12)
        w = _np(paddle.audio.functional.get_window("hamming", 17, fftbins=False))
        np.testing.assert_allclose(w, np.hamming(17), atol=1e-12)
        w = _np(paddle.audio.functional.get_window("blackman", 16, fftbins=False))
        np.testing.assert_allclose(w, np.blackman(16), atol=1e-12)

    def test_fbank_and_dct_shapes(self):
        fb = _np(paddle.audio.functional.compute_fbank_matrix(16000, 512,
                                                              n_mels=40))
        assert fb.shape == (40, 257) and (fb >= 0).all()
        dct = _np(paddle.audio.functional.create_dct(13, 40))
        assert dct.shape == (40, 13)

    def test_feature_layers(self):
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(2, 4000).astype(np.float32))
        spec = paddle.audio.features.Spectrogram(n_fft=256, hop_length=128)(x)
        assert _np(spec).shape[1] == 129
        mel = paddle.audio.features.MelSpectrogram(sr=8000, n_fft=256,
                                                   hop_length=128, n_mels=32)(x)
        assert _np(mel).shape[1] == 32
        mfcc = paddle.audio.features.MFCC(sr=8000, n_mfcc=13, n_fft=256,
                                          hop_length=128, n_mels=32)(x)
        assert _np(mfcc).shape[1] == 13
        assert np.isfinite(_np(mfcc)).all()

    def test_datasets(self):
        ds = paddle.audio.datasets.TESS(mode="dev", feat_type="raw")
        wav, label = ds[0]
        assert wav.shape == (16000,) and 0 <= label < 7


class TestLinalgExtras:
    def test_lu_unpack(self):
        a = np.random.RandomState(0).randn(5, 5)
        lu_mat, piv = paddle.linalg.lu(paddle.to_tensor(a))
        P, L, U = paddle.linalg.lu_unpack(lu_mat, piv)
        rec = _np(P) @ _np(L) @ _np(U)
        np.testing.assert_allclose(rec, a, rtol=1e-8, atol=1e-8)

    def test_top_level_linalg_namespace(self):
        for name in ["cholesky", "svd", "qr", "det", "solve", "pinv", "lstsq"]:
            assert hasattr(paddle.linalg, name)
