"""Tape autograd: backward, gradcheck, PyLayer, higher-order (ref paddle/autograd)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def finite_diff(f, x, eps=1e-3):
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[i] += eps
        xm[i] -= eps
        g[i] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), rtol=1e-6)

    def test_matmul_grad_fd(self):
        a = np.random.RandomState(0).randn(3, 3).astype(np.float64)

        def f(v):
            return float((v @ v).sum())

        x = paddle.to_tensor(a, stop_gradient=False)
        ((x @ x).sum()).backward()
        np.testing.assert_allclose(x.grad.numpy(), finite_diff(f, a), rtol=1e-3, atol=1e-4)

    def test_broadcast_grad(self):
        x = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]], stop_gradient=False)
        b = paddle.to_tensor([10.0, 20.0], stop_gradient=False)
        ((x + b) * 2).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 2.0))
        np.testing.assert_allclose(b.grad.numpy(), [4.0, 4.0])

    def test_grad_accumulation(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])

    def test_stop_gradient(self):
        x = paddle.to_tensor([1.0], stop_gradient=True)
        y = paddle.to_tensor([2.0], stop_gradient=False)
        (x * y).sum().backward()
        assert x.grad is None
        np.testing.assert_allclose(y.grad.numpy(), [1.0])

    def test_no_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_detach(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        d = x.detach()
        assert d.stop_gradient
        np.testing.assert_allclose(d.numpy(), x.numpy())

    def test_nonlinear_fd(self):
        a = np.random.RandomState(1).rand(4).astype(np.float64) + 0.5

        def f(v):
            return float(np.sum(np.log(v) * np.tanh(v) + np.exp(-v)))

        x = paddle.to_tensor(a, stop_gradient=False)
        (paddle.log(x) * paddle.tanh(x) + paddle.exp(-x)).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), finite_diff(f, a), rtol=1e-3, atol=1e-5)


class TestGradAPI:
    def test_paddle_grad(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x * x
        (gx,) = paddle.grad(y, x)
        np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-6)

    def test_higher_order(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x * x * x
        (g1,) = paddle.grad(y, x, create_graph=True)
        (g2,) = paddle.grad(g1, x)
        np.testing.assert_allclose(g2.numpy(), [12.0], rtol=1e-6)  # d2/dx2 x^3 = 6x


class TestPyLayer:
    def test_custom_vjp(self):
        from paddle_tpu.autograd import PyLayer

        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, dy):
                return dy * 2

        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = Double.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


class TestHooks:
    def test_register_hook(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        x.register_hook(lambda g: g * 10)
        (x * 1).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [10.0, 10.0])
