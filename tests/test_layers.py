"""nn.Layer system + individual layers (ref test/legacy_test layer tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestLayerBase:
    def test_parameters_and_state_dict(self):
        m = nn.Linear(4, 3)
        ps = list(m.parameters())
        assert len(ps) == 2
        sd = m.state_dict()
        assert set(sd) == {"weight", "bias"}
        m2 = nn.Linear(4, 3)
        m2.set_state_dict(sd)
        x = paddle.randn([2, 4])
        np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)

    def test_named_parameters_nested(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        names = [n for n, _ in m.named_parameters()]
        assert len(names) == 4
        sd = m.state_dict()
        assert len(sd) == 4

    def test_train_eval_mode(self):
        m = nn.Dropout(0.5)
        m.eval()
        x = paddle.ones([100])
        np.testing.assert_allclose(m(x).numpy(), np.ones(100))
        m.train()
        assert m.training

    def test_containers(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3
        x = paddle.ones([1, 2])
        for layer in ll:
            x = layer(x)
        ld = nn.LayerDict({"a": nn.Linear(2, 2)})
        assert "a" in ld

    def test_apply_and_sublayers(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
        count = []
        m.apply(lambda layer: count.append(type(layer).__name__))
        assert len(count) >= 3


class TestCommonLayers:
    def test_linear(self):
        m = nn.Linear(4, 3)
        out = m(paddle.randn([5, 4]))
        assert out.shape == [5, 3]
        ref = paddle.matmul(paddle.randn([1, 4]), m.weight) + m.bias
        assert ref.shape == [1, 3]

    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], dtype=np.int64))
        out = emb(ids)
        assert out.shape == [2, 2, 4]

    def test_flatten_identity(self):
        assert nn.Flatten()(paddle.ones([2, 3, 4])).shape == [2, 12]
        x = paddle.ones([2])
        assert nn.Identity()(x) is x


class TestConvPool:
    def test_conv2d(self):
        m = nn.Conv2D(3, 8, 3, padding=1)
        out = m(paddle.randn([2, 3, 16, 16]))
        assert out.shape == [2, 8, 16, 16]

    def test_conv2d_stride_groups(self):
        m = nn.Conv2D(4, 8, 3, stride=2, padding=1, groups=2)
        assert m(paddle.randn([1, 4, 8, 8])).shape == [1, 8, 4, 4]

    def test_conv1d_3d(self):
        assert nn.Conv1D(2, 4, 3)(paddle.randn([1, 2, 10])).shape == [1, 4, 8]
        assert nn.Conv3D(1, 2, 3)(paddle.randn([1, 1, 5, 5, 5])).shape == [1, 2, 3, 3, 3]

    def test_conv_transpose(self):
        m = nn.Conv2DTranspose(4, 2, 2, stride=2)
        assert m(paddle.randn([1, 4, 8, 8])).shape == [1, 2, 16, 16]

    def test_pools(self):
        x = paddle.randn([1, 3, 8, 8])
        assert nn.MaxPool2D(2)(x).shape == [1, 3, 4, 4]
        assert nn.AvgPool2D(2)(x).shape == [1, 3, 4, 4]
        assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 3, 1, 1]
        np.testing.assert_allclose(
            nn.AdaptiveAvgPool2D(1)(x).numpy().ravel(), x.numpy().mean(axis=(2, 3)).ravel(), rtol=1e-5)


class TestNorm:
    def test_layernorm_numeric(self):
        a = np.random.RandomState(0).randn(2, 5).astype(np.float32)
        m = nn.LayerNorm(5)
        out = m(paddle.to_tensor(a)).numpy()
        ref = (a - a.mean(-1, keepdims=True)) / np.sqrt(a.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_batchnorm_running_stats(self):
        m = nn.BatchNorm1D(4)
        x = paddle.to_tensor(np.random.RandomState(0).randn(16, 4).astype(np.float32) * 3 + 1)
        m.train()
        for _ in range(5):
            m(x)
        rm = m._mean.numpy() if hasattr(m, "_mean") else m.running_mean.numpy()
        assert abs(rm.mean() - 1.0) < 1.0  # moved toward batch mean
        m.eval()
        out_eval = m(x)
        assert out_eval.shape == [16, 4]

    def test_groupnorm_instancenorm_rmsnorm(self):
        x = paddle.randn([2, 6, 4, 4])
        assert nn.GroupNorm(3, 6)(x).shape == [2, 6, 4, 4]
        assert nn.InstanceNorm2D(6)(x).shape == [2, 6, 4, 4]


class TestActivation:
    def test_numeric(self):
        a = np.linspace(-3, 3, 13, dtype=np.float32)
        x = paddle.to_tensor(a)
        np.testing.assert_allclose(nn.ReLU()(x).numpy(), np.maximum(a, 0))
        np.testing.assert_allclose(nn.Sigmoid()(x).numpy(), 1 / (1 + np.exp(-a)), rtol=1e-5)
        np.testing.assert_allclose(nn.Silu()(x).numpy(), a / (1 + np.exp(-a)), rtol=1e-5)
        np.testing.assert_allclose(
            nn.LeakyReLU(0.1)(x).numpy(), np.where(a > 0, a, 0.1 * a), rtol=1e-6)
        sm = nn.Softmax()(paddle.to_tensor(a.reshape(1, -1))).numpy()
        np.testing.assert_allclose(sm.sum(), 1.0, rtol=1e-5)

    def test_gelu(self):
        import math
        a = np.linspace(-2, 2, 9, dtype=np.float32)
        out = nn.GELU()(paddle.to_tensor(a)).numpy()
        # exact gelu: x * 0.5 * (1 + erf(x/sqrt(2)))
        from math import erf
        ref = np.array([v * 0.5 * (1 + erf(v / math.sqrt(2))) for v in a], dtype=np.float32)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


class TestRNN:
    def test_lstm_shapes(self):
        m = nn.LSTM(4, 8, num_layers=2)
        x = paddle.randn([2, 5, 4])  # [batch, seq, feat]
        out, (h, c) = m(x)
        assert out.shape == [2, 5, 8]
        assert h.shape == [2, 2, 8]

    def test_gru_bidirectional(self):
        m = nn.GRU(4, 8, direction="bidirect")
        out, h = m(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 16]

    def test_simplernn(self):
        m = nn.SimpleRNN(4, 8)
        out, h = m(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 8]


class TestTransformer:
    def test_mha(self):
        m = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 6, 16])
        out = m(x, x, x)
        assert out.shape == [2, 6, 16]

    def test_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32)
        enc = nn.TransformerEncoder(layer, 2)
        out = enc(paddle.randn([2, 6, 16]))
        assert out.shape == [2, 6, 16]


class TestLoss:
    def test_cross_entropy(self):
        logits = paddle.to_tensor(np.random.RandomState(0).randn(4, 5).astype(np.float32))
        labels = paddle.to_tensor(np.array([0, 1, 2, 3], dtype=np.int64))
        loss = nn.CrossEntropyLoss()(logits, labels)
        lg = logits.numpy()
        p = np.exp(lg - lg.max(1, keepdims=True))
        p /= p.sum(1, keepdims=True)
        ref = -np.log(p[np.arange(4), [0, 1, 2, 3]]).mean()
        np.testing.assert_allclose(float(loss), ref, rtol=1e-5)

    def test_ce_ignore_index_and_smoothing(self):
        logits = paddle.randn([4, 5])
        labels = paddle.to_tensor(np.array([0, -100, 2, 3], dtype=np.int64))
        loss = nn.CrossEntropyLoss(ignore_index=-100)(logits, labels)
        assert np.isfinite(float(loss))
        loss2 = F.cross_entropy(logits, paddle.to_tensor(np.array([0, 1, 2, 3], dtype=np.int64)),
                                label_smoothing=0.1) if hasattr(F, "cross_entropy") else loss
        assert np.isfinite(float(loss2))

    def test_mse_l1_bce(self):
        x = paddle.to_tensor([1.0, 2.0])
        y = paddle.to_tensor([1.5, 1.5])
        np.testing.assert_allclose(float(nn.MSELoss()(x, y)), 0.25, rtol=1e-6)
        np.testing.assert_allclose(float(nn.L1Loss()(x, y)), 0.5, rtol=1e-6)
        p = paddle.to_tensor([0.6, 0.4])
        t = paddle.to_tensor([1.0, 0.0])
        ref = -(np.log(0.6) + np.log(0.6)) / 2
        np.testing.assert_allclose(float(nn.BCELoss()(p, t)), ref, rtol=1e-5)

    def test_loss_backward(self):
        m = nn.Linear(4, 3)
        x = paddle.randn([2, 4])
        y = paddle.to_tensor(np.array([0, 2], dtype=np.int64))
        loss = nn.CrossEntropyLoss()(m(x), y)
        loss.backward()
        assert m.weight.grad is not None
        assert np.isfinite(m.weight.grad.numpy()).all()


class TestFunctional:
    def test_one_hot_interpolate(self):
        oh = F.one_hot(paddle.to_tensor(np.array([0, 2], dtype=np.int64)), 3)
        np.testing.assert_allclose(oh.numpy(), [[1, 0, 0], [0, 0, 1]])
        up = F.interpolate(paddle.ones([1, 1, 4, 4]), scale_factor=2)
        assert up.shape == [1, 1, 8, 8]

    def test_sdpa(self):
        q = paddle.randn([2, 5, 4, 8])  # b s h d
        out = F.scaled_dot_product_attention(q, q, q)
        assert out.shape == [2, 5, 4, 8]

    def test_softmax_logsoftmax(self):
        a = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        s = F.softmax(paddle.to_tensor(a), axis=-1).numpy()
        np.testing.assert_allclose(s.sum(-1), np.ones(3), rtol=1e-5)
        ls = F.log_softmax(paddle.to_tensor(a), axis=-1).numpy()
        np.testing.assert_allclose(np.exp(ls), s, rtol=1e-5)


class TestInitClip:
    def test_initializers(self):
        from paddle_tpu.nn import initializer as init
        w = paddle.create_parameter([64, 64], "float32", default_initializer=init.XavierNormal()) \
            if hasattr(paddle, "create_parameter") else None
        m = nn.Linear(64, 64, weight_attr=None)
        assert np.isfinite(m.weight.numpy()).all()

    def test_clip_grad_by_global_norm(self):
        clip = nn.ClipGradByGlobalNorm(1.0)
        m = nn.Linear(4, 4)
        x = paddle.randn([8, 4])
        (m(x) ** 2).sum().backward()
        # applied by optimizer; check the object exists and is callable machinery
        assert clip.clip_norm == 1.0


class TestConvTransposeSame:
    def test_same_padding_shapes_and_adjoint(self):
        """SAME conv_transpose (paddle/TF semantics: out = in * stride) is
        the exact adjoint of SAME conv — <conv(x), g> == <x, convT(g)>."""
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 3, 8, 8).astype(np.float32))
        w = paddle.to_tensor(rng.randn(5, 3, 3, 3).astype(np.float32))
        y = F.conv2d(x, w, stride=2, padding="SAME")
        assert list(y.shape) == [2, 5, 4, 4]
        g = paddle.to_tensor(rng.randn(2, 5, 4, 4).astype(np.float32))
        z = F.conv2d_transpose(g, w, stride=2, padding="SAME")
        assert list(z.shape) == [2, 3, 8, 8]  # in * stride
        lhs = float((np.asarray(y.numpy()) * np.asarray(g.numpy())).sum())
        rhs = float((np.asarray(x.numpy()) * np.asarray(z.numpy())).sum())
        np.testing.assert_allclose(lhs, rhs, rtol=1e-5)

    def test_same_padding_1d(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(1)
        g = paddle.to_tensor(rng.randn(2, 4, 5).astype(np.float32))
        w = paddle.to_tensor(rng.randn(4, 3, 3).astype(np.float32))
        z = F.conv1d_transpose(g, w, stride=3, padding="SAME")
        assert list(z.shape) == [2, 3, 15]
