"""SLO-driven multi-tenant serving (serving/slo.py + the class-aware
scheduler/engine/supervisor wiring).

Gates:
  * flags off = the strict-FCFS default path (the parity suites cover
    bitwise; here: no policy object is even constructed);
  * class-aware admission (interactive first) + WFQ tenant fairness,
    incl. weights;
  * preemptive admission: a deadline-at-risk interactive evicts the
    youngest best_effort slot, whose replay stays BITWISE (the PR 7
    requeue machinery);
  * load shedding: sustained overload sheds lowest-class queued work
    with retry-after hints from the live drain rate, refuses new
    best_effort while latched, recovers, and the ledger/summary show it;
  * unified deadline boundary (now >= deadline) + queue-wait recording
    for EXPIRED/SHED;
  * hot weight swap: same-shape, zero retraces, prefix cache
    invalidated, version stamped end to end (results, snapshots,
    telemetry), version-mismatched snapshots fall back to replay;
  * autoscaler policy (hysteresis + cooldown) and supervisor
    grow/shrink through the spawn/drain machinery;
  * per-tenant token-bucket rate limits (ShedError with exact hints);
  * the satellite fixes: draining replicas unroutable, fleet-wide
    QueueFullError totals;
  * the tools_slo_smoke.py chaos ladder (quick rungs in tier-1, the p99
    gate slow-marked).
"""
import os
import time

import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.serving import metrics as smetrics
from paddle_tpu.serving import ShedError
from paddle_tpu.serving.slo import Autoscaler, DrainRate, TokenBucket
from paddle_tpu.models.generation import generate_from_params
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import init_gpt_params
from paddle_tpu.utils import fault_injection as fi

CFG = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=128, dropout=0.0, use_flash=False,
                compute_dtype="float32", remat=False)
_PARAMS = {}


def _params(seed=0):
    if seed not in _PARAMS:
        _PARAMS[seed] = init_gpt_params(CFG, jax.random.key(seed))
    return _PARAMS[seed]


def _engine(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("kv_layout", "paged")
    params = kw.pop("params", None)
    return serving.Engine(params=params if params is not None else _params(),
                          config=CFG, **kw)


def _ref(prompt, max_new, params_seed=0, **kw):
    out = np.asarray(generate_from_params(
        _params(params_seed), np.asarray(prompt)[None], CFG,
        max_new_tokens=max_new, **kw)._data)
    return out[0, len(prompt):].tolist()


@pytest.fixture(autouse=True)
def _reset():
    smetrics.reset_serving_counters()
    yield
    paddle.set_flags({
        "FLAGS_serving_priority_classes": False,
        "FLAGS_serving_shed": False,
        "FLAGS_serving_shed_window": 4,
        "FLAGS_serving_preempt_margin_s": 0.0,
        "FLAGS_serving_tenant_rate": 0.0,
        "FLAGS_serving_autoscale": False,
        "FLAGS_serving_class_deadline_interactive": 0.0,
    })
    fi.deactivate()


# ---------------------------------------------------------------------------
# defaults / request surface


def test_flags_off_no_policy_objects():
    """Default engine: strict FCFS, no shed policy, no class deadlines —
    the pre-SLO path (whose bitwise parity the serving suites gate)."""
    eng = _engine()
    assert eng.priority_mode is False
    assert eng._shed is None
    assert eng.scheduler.priority is False
    assert eng.params_version == 0
    # priority/tenant are carried but inert: a best_effort request is
    # served strict-FCFS behind an earlier batch one
    a = serving.Request(np.arange(1, 6), max_new_tokens=2,
                        priority="best_effort")
    b = serving.Request(np.arange(2, 7), max_new_tokens=2,
                        priority="interactive")
    eng1 = _engine(num_slots=1)
    eng1.submit(a)
    eng1.submit(b)
    res = eng1.run()
    assert res[a.request_id].ttft < res[b.request_id].ttft  # FCFS held


def test_unknown_priority_class_rejected():
    with pytest.raises(ValueError, match="unknown priority class"):
        serving.Request(np.arange(1, 4), priority="platinum")


def test_request_state_roundtrip_carries_slo_fields():
    r = serving.Request(np.arange(1, 6), max_new_tokens=3,
                        priority="best_effort", tenant="acme")
    r.params_version = 5
    s = r.to_state()
    r2 = serving.Request.from_state(s)
    assert (r2.priority, r2.tenant, r2.params_version) == \
        ("best_effort", "acme", 5)
    c = r.replay_copy()
    assert (c.priority, c.tenant) == ("best_effort", "acme")
    # results carry them too
    r._finish(serving.LENGTH)
    res = r.result()
    assert (res.priority, res.tenant, res.params_version) == \
        ("best_effort", "acme", 5)


def test_deadline_boundary_unified():
    """ONE boundary predicate everywhere: expired from the first instant
    now >= deadline (the deadline itself is outside the window)."""
    r = serving.Request(np.arange(1, 4), deadline_s=5.0)
    r.submit_t = 100.0
    assert not r.expired(104.999)
    assert r.expired(105.0)          # the boundary instant counts
    assert r.expired(105.001)
    # scheduler.expire and admit use the same predicate
    sched = serving.Scheduler((16,))
    sched.submit(r)
    assert sched.expire(now=104.9) == []
    expired = sched.expire(now=105.0)
    assert expired == [r] and r.finish_reason == serving.EXPIRED


# ---------------------------------------------------------------------------
# class-aware admission + WFQ


def _queued(prompt_start, cls="batch", tenant="default", t=None):
    r = serving.Request(np.arange(prompt_start, prompt_start + 4),
                        max_new_tokens=2, priority=cls, tenant=tenant)
    return r


def test_priority_admission_interactive_first():
    sched = serving.Scheduler((16,), priority=True)
    be = _queued(1, "best_effort")
    ba = _queued(2, "batch")
    ia = _queued(3, "interactive")
    for r in (be, ba, ia):
        sched.submit(r)
    order = sched._admission_order()
    assert order == [ia, ba, be]
    admitted, _ = sched.admit(2, now=time.perf_counter())
    assert admitted == [ia, ba]


def test_wfq_tenant_fairness_and_weights():
    """Within a class, tenants round-robin: a flood from tenant A cannot
    starve tenant B; a weight-2 tenant gets two slots per rotation."""
    sched = serving.Scheduler((16,), priority=True)
    a = [_queued(10 + i, tenant="A") for i in range(4)]
    b = [_queued(30 + i, tenant="B") for i in range(2)]
    for r in a[:2] + b[:1] + a[2:] + b[1:]:   # A,A,B,A,A,B arrival
        sched.submit(r)
    order = sched._admission_order()
    assert order[:4] == [a[0], b[0], a[1], b[1]]  # interleaved
    # weights: A earns 2 pops per rotation
    sched2 = serving.Scheduler((16,), priority=True,
                               tenant_weights={"A": 2})
    for r in a[:2] + b[:1] + a[2:] + b[1:]:
        sched2.submit(r)
    order2 = sched2._admission_order()
    assert order2[:3] == [a[0], a[1], b[0]]
    # the rotation pointer survives admissions: after serving A's credit,
    # the next boundary starts at B
    admitted, _ = sched2.admit(2, now=time.perf_counter())
    assert admitted == [a[0], a[1]]
    assert sched2._admission_order()[0] == b[0]


def test_engine_serves_interactive_before_earlier_best_effort():
    eng = _engine(num_slots=1, priority=True)
    blocker = serving.Request(np.arange(3, 8), max_new_tokens=6)
    be = serving.Request(np.arange(1, 6), max_new_tokens=3,
                         priority="best_effort")
    ia = serving.Request(np.arange(2, 7), max_new_tokens=3,
                         priority="interactive")
    eng.submit(blocker)
    eng.step()
    eng.submit(be)       # arrives FIRST
    eng.submit(ia)       # but outranks it
    res = eng.run()
    assert res[ia.request_id].ttft < res[be.request_id].ttft
    # both still bitwise (admission order never changes content)
    assert res[be.request_id].tokens == _ref(be.prompt, 3)
    assert res[ia.request_id].tokens == _ref(ia.prompt, 3)


def test_class_default_deadline_applied_in_priority_mode():
    paddle.set_flags({"FLAGS_serving_class_deadline_interactive": 7.5})
    eng = _engine(priority=True)
    r = serving.Request(np.arange(1, 5), max_new_tokens=1,
                        priority="interactive")
    eng.submit(r)
    assert r.deadline_s == 7.5
    # explicit deadlines win; flags-off engines never stamp
    r2 = serving.Request(np.arange(1, 5), max_new_tokens=1,
                         priority="interactive", deadline_s=1.0)
    eng.submit(r2)
    assert r2.deadline_s == 1.0
    eng_off = _engine()
    r3 = serving.Request(np.arange(2, 6), max_new_tokens=1,
                         priority="interactive")
    eng_off.submit(r3)
    assert r3.deadline_s is None
    eng.run()
    eng_off.run()


# ---------------------------------------------------------------------------
# preemptive admission


def test_preemption_evicts_best_effort_bitwise_replay():
    """A deadline-at-risk interactive evicts the running best_effort; the
    victim requeues at its ORIGINAL arrival and its replay is bitwise."""
    paddle.set_flags({"FLAGS_serving_preempt_margin_s": 60.0})
    eng = _engine(num_slots=1, priority=True)
    victim = serving.Request(np.arange(1, 6), max_new_tokens=8,
                             priority="best_effort")
    eng.submit(victim)
    for _ in range(3):
        eng.step()
    assert victim.tokens                      # mid-flight, tokens streamed
    urgent = serving.Request(np.arange(2, 7), max_new_tokens=2,
                             priority="interactive", deadline_s=50.0)
    eng.submit(urgent)
    res = eng.run()
    c = smetrics.serving_counters()
    assert c["preempted"] == 1
    assert res[urgent.request_id].finish_reason == "length"
    assert res[victim.request_id].tokens == _ref(victim.prompt, 8)
    assert res[victim.request_id].finish_reason == "length"
    # exactly one TTFT sample each despite the victim's round trip
    assert len(smetrics._ttft) == 2


def test_no_preemption_without_deadline_risk():
    """Queued interactive WITHOUT a deadline (or with ample slack) never
    evicts anyone — preemption is deadline-driven, not class-driven."""
    paddle.set_flags({"FLAGS_serving_preempt_margin_s": 0.01})
    eng = _engine(num_slots=1, priority=True)
    victim = serving.Request(np.arange(1, 6), max_new_tokens=6,
                             priority="best_effort")
    eng.submit(victim)
    eng.step()
    eng.submit(serving.Request(np.arange(2, 7), max_new_tokens=2,
                               priority="interactive"))          # no deadline
    eng.submit(serving.Request(np.arange(3, 8), max_new_tokens=2,
                               priority="interactive",
                               deadline_s=3600.0))               # huge slack
    eng.run()
    assert smetrics.serving_counters()["preempted"] == 0


def test_preemption_never_evicts_same_or_better_class():
    paddle.set_flags({"FLAGS_serving_preempt_margin_s": 60.0})
    eng = _engine(num_slots=1, priority=True)
    first = serving.Request(np.arange(1, 6), max_new_tokens=6,
                            priority="interactive")
    eng.submit(first)
    eng.step()
    eng.submit(serving.Request(np.arange(2, 7), max_new_tokens=2,
                               priority="interactive", deadline_s=50.0))
    eng.run()
    assert smetrics.serving_counters()["preempted"] == 0


# ---------------------------------------------------------------------------
# load shedding


def _overload_engine(**kw):
    paddle.set_flags({"FLAGS_serving_shed_window": 2})
    return _engine(num_slots=1, priority=True, shed=True, max_queue=8, **kw)


def test_shed_lowest_class_with_retry_after():
    eng = _overload_engine()
    reqs = [serving.Request(np.arange(1, 6), max_new_tokens=4,
                            priority="interactive" if i == 0
                            else "best_effort")
            for i in range(8)]
    for r in reqs:
        eng.submit(r)
    for _ in range(3):
        eng.step()
    c = smetrics.serving_counters()
    assert c["shed"] > 0
    assert c["shed_queue_wait_s"] > 0         # refused work stays visible
    res = eng.run()
    shed = [r for r in res.values() if r.finish_reason == serving.SHED]
    assert shed
    assert all(r.retry_after is not None and r.retry_after > 0
               for r in shed)
    assert all(r.priority != "interactive" for r in shed)
    # the interactive request survived the overload
    assert res[reqs[0].request_id].finish_reason in ("stop", "length")
    assert "slo:" in smetrics.serving_summary()


def test_shed_refuses_new_best_effort_while_latched_then_recovers():
    eng = _overload_engine()
    for i in range(8):
        eng.submit(serving.Request(np.arange(1, 6), max_new_tokens=4,
                                   priority="best_effort"))
    for _ in range(3):
        eng.step()
    assert eng._shed.shedding
    with pytest.raises(ShedError) as ei:
        eng.submit(serving.Request(np.arange(9, 14), max_new_tokens=2,
                                   priority="best_effort"))
    assert ei.value.retry_after > 0
    assert ei.value.qsize is not None and ei.value.max_queue == 8
    # batch/interactive still accepted while best_effort sheds
    ok = serving.Request(np.arange(2, 7), max_new_tokens=2,
                         priority="batch")
    eng.submit(ok)
    eng.run()
    assert not eng._shed.shedding             # drained: latch released
    late = serving.Request(np.arange(3, 8), max_new_tokens=2,
                           priority="best_effort")
    eng.submit(late)
    res = eng.run()
    assert res[late.request_id].finish_reason in ("stop", "length")


def test_queue_wait_recorded_for_expired():
    eng = _engine(num_slots=1)
    blocker = serving.Request(np.arange(3, 8), max_new_tokens=8)
    doomed = serving.Request(np.arange(1, 6), max_new_tokens=2,
                             deadline_s=0.001)
    eng.submit(blocker)
    eng.step()
    eng.submit(doomed)
    time.sleep(0.01)
    res = eng.run()
    assert res[doomed.request_id].finish_reason == serving.EXPIRED
    c = smetrics.serving_counters()
    assert c["expired"] == 1
    assert c["expired_queue_wait_s"] > 0
    assert c["expired_queue_wait_mean"] > 0


# ---------------------------------------------------------------------------
# slo.py policy units


def test_token_bucket_exact_hints():
    tb = TokenBucket(rate=2.0, burst=2)
    assert tb.take(now=10.0) == 0.0
    assert tb.take(now=10.0) == 0.0
    wait = tb.take(now=10.0)                   # burst spent
    assert wait == pytest.approx(0.5)          # 1 token / 2 per s
    assert tb.take(now=10.5) == 0.0            # accrued exactly on time
    assert tb.take(now=10.5) == pytest.approx(0.5)


def test_drain_rate_retry_after():
    dr = DrainRate(alpha=1.0)
    dr.observe(0, now=0.0)
    dr.observe(10, now=1.0)                    # 10 resolved/s
    assert dr.rate == pytest.approx(10.0)
    assert dr.retry_after(20) == pytest.approx(2.0)
    assert dr.retry_after(-5) == 0.05          # floor
    assert DrainRate().retry_after(1000, ceil=60.0) == 60.0


def test_autoscaler_hysteresis_and_cooldown():
    a = Autoscaler(min_replicas=1, max_replicas=3, up_queue=4.0,
                   down_queue=0.5, up_occupancy=0.9, down_occupancy=0.2,
                   window=2, cooldown_s=10.0)
    # one hot sample: below window, no action
    assert a.decide(1, 10, 2, 2, now=0.0) is None
    assert a.decide(1, 10, 2, 2, now=1.0) == "grow"
    # cooldown: still hot, but too soon
    assert a.decide(2, 20, 4, 4, now=2.0) is None
    assert a.decide(2, 20, 4, 4, now=5.0) is None
    assert a.decide(2, 20, 4, 4, now=12.0) == "grow"
    # dead band resets both streaks
    assert a.decide(3, 6, 3, 6, now=30.0) is None
    assert a.decide(3, 0, 0, 6, now=31.0) is None
    assert a.decide(3, 0, 0, 6, now=32.0) == "shrink"
    # bounds respected
    assert a.decide(1, 0, 0, 2, now=60.0) is None    # min_replicas
    b = Autoscaler(max_replicas=1, up_queue=1.0, window=1, cooldown_s=0.0)
    assert b.decide(1, 10, 2, 2, now=0.0) is None    # max_replicas


def test_autoscaler_ttft_slo_trigger():
    a = Autoscaler(min_replicas=1, max_replicas=2, up_queue=1e9,
                   up_occupancy=2.0, ttft_slo_s=0.1, window=1,
                   cooldown_s=0.0)
    assert a.decide(1, 0, 0, 2, ttft_p99=0.05, now=0.0) is None
    assert a.decide(1, 0, 0, 2, ttft_p99=0.5, now=1.0) == "grow"


def test_arrival_surge_deterministic_and_inactive_zero():
    s1 = fi.ArrivalSurge(base_rate=0.5, surge_rate=4.0, surge_start=2,
                         surge_steps=4, total_steps=16, seed=3)
    s2 = fi.ArrivalSurge(base_rate=0.5, surge_rate=4.0, surge_start=2,
                         surge_steps=4, total_steps=16, seed=3)
    assert s1.counts.tolist() == s2.counts.tolist()
    assert s1.in_surge(3) and not s1.in_surge(6)
    assert s1.arrivals(999) == 0
    fi.deactivate()
    assert fi.surge_arrivals(0) == 0          # no plan: zero-cost zero
    with fi.inject(fi.FaultPlan(surge=s1)):
        total = sum(fi.surge_arrivals(i) for i in range(16))
    assert total == int(s1.counts.sum())
    assert fi.stats()["surged_arrivals"] == total


# ---------------------------------------------------------------------------
# hot weight swap


def test_swap_params_bitwise_no_retrace_cache_invalidated():
    eng = _engine(num_slots=2)
    r1 = serving.Request(np.arange(1, 6), max_new_tokens=3)
    out_v0 = eng.run([r1])[r1.request_id]
    assert out_v0.params_version == 0
    traces = smetrics.serving_counters()["paged_traces"]
    eng.swap_params(_params(1), version=7)
    # SAME prompt: a stale prefix-cache hit would serve v0 KV
    r2 = serving.Request(np.arange(1, 6), max_new_tokens=3)
    res = eng.run([r2])[r2.request_id]
    assert res.tokens == _ref(r2.prompt, 3, params_seed=1)
    assert res.params_version == 7
    assert smetrics.serving_counters()["paged_traces"] == traces
    assert smetrics.serving_counters()["weight_swaps"] == 1


def test_swap_params_guards():
    eng = _engine(num_slots=1)
    eng.submit(serving.Request(np.arange(1, 6), max_new_tokens=4))
    eng.step()
    with pytest.raises(RuntimeError, match="non-idle"):
        eng.swap_params(_params(1))
    eng.run()
    bad = jax.tree_util.tree_map(lambda x: x[..., :1], _params(1))
    with pytest.raises(ValueError):
        eng.swap_params(bad)


def test_snapshot_carries_version_and_mismatch_rejected(tmp_path):
    eng = _engine(num_slots=1)
    eng.submit(serving.Request(np.arange(1, 8), max_new_tokens=8))
    for _ in range(3):
        eng.step()
    snap = eng.state_dict()
    assert snap["meta"]["params_version"] == 0
    # an upgraded engine must NOT resume old-version KV mid-stream
    eng2 = _engine(num_slots=1)
    eng2.swap_params(_params(1), version=1)
    with pytest.raises(ValueError, match="snapshot meta"):
        eng2.load_state_dict(snap)
    # same-version engine restores and finishes bitwise
    eng3 = _engine(num_slots=1)
    eng3.load_state_dict(snap)
    res = eng3.run()
    (only,) = res.values()
    assert only.tokens == _ref(np.arange(1, 8), 8)


def test_rolling_restart_new_params_single_version_zero_drops():
    """Upgrade under load: zero drops, every result single-version
    bitwise, fleet converges, future respawns serve the new weights."""
    def factory():
        return _engine(num_slots=2, max_queue=64)

    sup = serving.ServingSupervisor(factory, num_replicas=2)
    rng = np.random.default_rng(5)
    reqs = []
    for i in range(10):
        kw = ({"do_sample": True, "temperature": 0.8, "top_p": 0.9,
               "seed": 40 + i} if i % 2 else {})
        reqs.append(serving.Request(rng.integers(0, 97, 4 + i % 3),
                                    max_new_tokens=3 + i % 3, **kw))
    for r in reqs:
        sup.submit(r)
    for _ in range(2):
        sup.step()
    sup.rolling_restart(new_params=_params(1))
    res = sup.run()
    assert len(res) == len(reqs)
    for r in reqs:
        out = res[r.request_id]
        assert out.finish_reason in ("stop", "length")
        kw = ({"do_sample": True, "temperature": r.temperature,
               "top_p": r.top_p, "seed": r.seed} if r.do_sample else {})
        assert out.tokens == _ref(r.prompt, r.max_new_tokens,
                                  params_seed=out.params_version, **kw), \
            f"request {r.request_id} not single-version consistent"
    c = smetrics.serving_counters()
    assert c["dropped"] == 0
    assert c["rolling_restarts"] == 1
    assert sup.telemetry()["params_version"] == 1
    for rep in sup._replicas:
        assert rep.engine.params_version == 1
    # a crash respawn AFTER the upgrade serves the new weights too
    sup._on_failure(sup._replicas[0], RuntimeError("boom"))
    assert sup._replicas[0].engine.params_version == 1


# ---------------------------------------------------------------------------
# supervisor: autoscale, rate limits, satellite fixes


def _factory():
    return _engine(num_slots=2, max_queue=64)


def test_supervisor_autoscale_grow_and_shrink():
    sup = serving.ServingSupervisor(
        _factory, num_replicas=1,
        autoscale=Autoscaler(min_replicas=1, max_replicas=3, up_queue=1.0,
                             down_queue=0.5, down_occupancy=0.3, window=1,
                             cooldown_s=0.0))
    reqs = [serving.Request(np.arange(1, 6) + i, max_new_tokens=4)
            for i in range(12)]
    for r in reqs:
        sup.submit(r)
    sup.step()
    assert sup.alive_replicas > 1             # grew under backlog
    res = sup.run()
    assert len(res) == len(reqs)
    for _ in range(10):                       # idle: shrinks back to min
        sup.step()
    assert sup.alive_replicas == 1
    c = smetrics.serving_counters()
    assert c["scale_ups"] >= 1 and c["scale_downs"] >= 1
    assert c["dropped"] == 0
    # retired replicas stay indexed (owner bookkeeping never shifts)
    assert len(sup._replicas) > sup.alive_replicas


def test_supervisor_tenant_rate_limit():
    sup = serving.ServingSupervisor(_factory, num_replicas=1,
                                    tenant_rate=0.001, tenant_burst=2)
    for _ in range(2):
        sup.submit(serving.Request(np.arange(1, 6), max_new_tokens=1,
                                   tenant="noisy"))
    with pytest.raises(ShedError) as ei:
        sup.submit(serving.Request(np.arange(1, 6), max_new_tokens=1,
                                   tenant="noisy"))
    assert ei.value.retry_after > 0
    # fleet-wide fields ride along; other tenants unaffected
    assert ei.value.max_queue == 64
    sup.submit(serving.Request(np.arange(1, 6), max_new_tokens=1,
                               tenant="quiet"))
    assert smetrics.serving_counters()["rate_limited"] == 1
    sup.run()


def test_submit_never_routes_to_draining_replica():
    """Regression (satellite): the spill check used to compare only queue
    depth, so a replica mid-drain (rolling restart) could be picked and
    the submit would explode with EngineStoppedError."""
    sup = serving.ServingSupervisor(_factory, num_replicas=2)
    sup._replicas[0].engine.drain()           # mid-rolling-restart state
    r = sup.submit(serving.Request(np.arange(1, 6), max_new_tokens=2))
    assert sup._owner[r.request_id] == 1      # routed around the drain
    res = sup.run()
    assert res[r.request_id].finish_reason in ("stop", "length")
    # with EVERY replica draining, submit reports no live replica instead
    # of exploding inside a drained engine
    sup2 = serving.ServingSupervisor(_factory, num_replicas=1)
    sup2._replicas[0].engine.drain()
    with pytest.raises(serving.EngineStoppedError):
        sup2.submit(serving.Request(np.arange(1, 6), max_new_tokens=2))


def test_queue_full_error_reports_fleet_totals():
    sup = serving.ServingSupervisor(
        lambda: _engine(num_slots=1, max_queue=2), num_replicas=2)
    for i in range(4):
        sup.submit(serving.Request(np.arange(1, 6) + i, max_new_tokens=2))
    with pytest.raises(serving.QueueFullError) as ei:
        sup.submit(serving.Request(np.arange(9, 14), max_new_tokens=2))
    assert ei.value.qsize == 4                # fleet-wide, not last-probed
    assert ei.value.max_queue == 4
    sup.run()


def test_supervisor_spills_past_shedding_replica_fleet_shed_error():
    """A shed-latched replica is probed, not trial-submitted: best_effort
    work spills to a healthy replica; only when EVERY candidate is
    latched/full does ShedError surface — with fleet-wide totals and the
    largest drain hint (never a replica-local engine ShedError)."""
    sup = serving.ServingSupervisor(
        lambda: _engine(num_slots=2, shed=True, max_queue=8),
        num_replicas=2)
    sup._replicas[0].engine._shed.shedding = True
    r = sup.submit(serving.Request(np.arange(1, 6), max_new_tokens=2,
                                   priority="best_effort"))
    assert sup._owner[r.request_id] == 1      # spilled past the latch
    sup._replicas[1].engine._shed.shedding = True
    with pytest.raises(ShedError) as ei:
        sup.submit(serving.Request(np.arange(2, 7), max_new_tokens=2,
                                   priority="best_effort"))
    assert ei.value.max_queue == 16           # fleet-wide, both replicas
    assert ei.value.retry_after > 0
    # batch class is not shed-refused: still routable while latched
    ok = sup.submit(serving.Request(np.arange(3, 8), max_new_tokens=2,
                                    priority="batch"))
    sup._replicas[0].engine._shed.shedding = False
    sup._replicas[1].engine._shed.shedding = False
    res = sup.run()
    assert res[ok.request_id].finish_reason in ("stop", "length")


def test_preemption_seats_the_at_risk_request_not_wfq_next():
    """The freed slot goes to the deadline-holder the eviction was FOR —
    not to whoever the deadline-blind WFQ rotation would pick next."""
    paddle.set_flags({"FLAGS_serving_preempt_margin_s": 60.0})
    eng = _engine(num_slots=1, priority=True)
    victim = serving.Request(np.arange(1, 6), max_new_tokens=8,
                             priority="best_effort")
    eng.submit(victim)
    eng.step()
    # same class, EARLIER arrival, no deadline: WFQ/FCFS would pick this
    calm = serving.Request(np.arange(2, 7), max_new_tokens=2,
                           priority="interactive", tenant="A")
    eng.submit(calm)
    urgent = serving.Request(np.arange(3, 8), max_new_tokens=2,
                             priority="interactive", tenant="B",
                             deadline_s=50.0)
    eng.submit(urgent)
    eng.step()
    # seated by the preemption (and already producing tokens — the fused
    # step can finish a short request within the boundary); the WFQ-next
    # same-class request is still waiting
    assert urgent.tokens and urgent.state in (serving.RUNNING,
                                              serving.FINISHED)
    assert calm.state == serving.QUEUED and not calm.tokens
    res = eng.run()
    assert smetrics.serving_counters()["preempted"] == 1
    for r in (victim, calm, urgent):
        assert res[r.request_id].tokens == \
            _ref(r.prompt, r.max_new_tokens)


def test_weight_swaps_counts_upgrades_not_respawns():
    """One upgrade on N replicas = N swaps in the ledger; later crash
    respawns RE-apply the live weights without inflating the audit
    trail."""
    sup = serving.ServingSupervisor(_factory, num_replicas=2)
    sup.rolling_restart(new_params=_params(1))
    assert smetrics.serving_counters()["weight_swaps"] == 2
    sup._on_failure(sup._replicas[0], RuntimeError("crash"))
    assert sup._replicas[0].engine.params_version == 1
    assert smetrics.serving_counters()["weight_swaps"] == 2   # unchanged


def test_capacity_probe_never_evicts_prefix_cache():
    """_capacity_for's paged probe answers from free + reclaimable counts
    without allocating: a transient probe must not churn the LRU cache
    (pool.try_alloc would evict entries to satisfy it)."""
    eng = _engine(num_slots=2, num_pages=13)    # tight pool (1 is trash)
    warm = serving.Request(np.arange(1, 17), max_new_tokens=2)
    eng.run([warm])                             # registers prefix pages
    pool = eng.pool
    entries = pool.cache_entries
    assert entries > 0
    free0 = pool.free_count
    big = serving.Request(np.arange(30, 70), max_new_tokens=40)
    probe = eng._capacity_for(big)              # needs cache reclaim space
    assert pool.cache_entries == entries        # cache untouched
    assert pool.free_count == free0             # nothing allocated
    # and the probe agrees with what a real reservation could do
    assert probe == pool.can_alloc(
        serving.pages_for(big.prompt_len + big.max_new_tokens,
                          eng.page_size))


def test_token_bucket_map_bounded():
    tb = TokenBucket(rate=1.0, burst=2)
    assert tb.idle_full(now=0.0)                # untouched = fresh
    tb.take(now=0.0)
    assert not tb.idle_full(now=0.5)
    assert tb.idle_full(now=5.0)                # refilled to burst
    sup = serving.ServingSupervisor(_factory, num_replicas=1,
                                    tenant_rate=100.0, tenant_burst=2)
    for i in range(1100):                       # rotating tenant ids
        sup._buckets[f"t{i}"] = TokenBucket(100.0, 2)
    sup._rate_limit(serving.Request(np.arange(1, 4), tenant="live"))
    assert len(sup._buckets) <= 2               # stale buckets swept


def test_shed_queue_wait_mean_counts_only_queued_sheds():
    """Up-front ShedError refusals bump 'shed' but carry no queue wait;
    the mean divides by the recorded-wait count so it is not diluted."""
    smetrics.observe_queue_wait(0.2, "shed")
    smetrics.bump("shed", 5)                  # 4 up-front refusals ride on
    c = smetrics.serving_counters()
    assert c["shed_queue_waits"] == 1
    assert c["shed_queue_wait_mean"] == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# the chaos ladder (quick rungs tier-1, p99 gate slow)


def _load_smoke():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "tools_slo_smoke",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools_slo_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_slo_smoke_quick_ladder():
    """tools_slo_smoke's structural rungs: surge→shed→recover,
    upgrade-under-load (single-version bitwise), kill-during-surge."""
    smoke = _load_smoke()
    out = smoke.run_ladder(full=False)
    for rung, info in out.items():
        assert info["ok"], (rung, info)


@pytest.mark.slow
def test_slo_smoke_p99_gate():
    """The timing-sensitive gate: interactive-class p99 TTFT held through
    surge + hot weight swap + replica kill."""
    smoke = _load_smoke()
    info = smoke.rung_p99_held()
    assert info["ok"], info
