"""to_static/jit, TrainStep, amp auto_cast + GradScaler (ref test/dygraph_to_static, test/amp)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


class TestToStatic:
    def test_fn_matches_eager(self):
        def f(x):
            return paddle.tanh(x) * 2 + 1

        sf = paddle.jit.to_static(f)
        x = paddle.randn([4, 4])
        np.testing.assert_allclose(sf(x).numpy(), f(x).numpy(), rtol=1e-6)

    def test_layer_matches_eager(self):
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = paddle.randn([3, 4])
        eager = m(x).numpy()
        sm = paddle.jit.to_static(m)
        np.testing.assert_allclose(sm(x).numpy(), eager, rtol=1e-5)

    def test_input_spec(self):
        from paddle_tpu.static import InputSpec
        def f(x):
            return x * 2
        sf = paddle.jit.to_static(f, input_spec=[InputSpec([None, 4], "float32")])
        out = sf(paddle.ones([2, 4]))
        np.testing.assert_allclose(out.numpy(), np.full((2, 4), 2.0))

    def test_hlo_introspection(self):
        def f(x):
            return x + 1
        sf = paddle.jit.to_static(f)
        txt = sf.hlo(paddle.ones([2]))
        assert isinstance(txt, str) and len(txt) > 0

    def test_save_load_roundtrip(self):
        m = nn.Linear(4, 2)
        x = paddle.randn([3, 4])
        ref = m(x).numpy()
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "model")
            paddle.jit.save(m, path, input_spec=[paddle.static.InputSpec([None, 4], "float32")])
            loaded = paddle.jit.load(path)
            np.testing.assert_allclose(loaded(x).numpy(), ref, rtol=1e-5)


class TestTrainStep:
    def test_fused_train_step(self):
        m = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = paddle.optimizer.AdamW(learning_rate=0.05)
        loss_fn = nn.MSELoss()
        step = paddle.jit.TrainStep(m, loss_fn, opt)
        rng = np.random.RandomState(0)
        X = rng.randn(32, 4).astype(np.float32)
        Y = (X @ rng.randn(4, 1)).astype(np.float32)
        losses = [float(step(paddle.to_tensor(X), paddle.to_tensor(Y))) for _ in range(60)]
        assert losses[-1] < 0.2 * losses[0], f"no convergence: {losses[0]} -> {losses[-1]}"

    def test_sync_to_model(self):
        m = nn.Linear(4, 1)
        step = paddle.jit.TrainStep(m, nn.MSELoss(), paddle.optimizer.SGD(learning_rate=0.1))
        w_before = m.weight.numpy().copy()
        step(paddle.randn([8, 4]), paddle.randn([8, 1]))
        step.sync_to_model()
        assert not np.allclose(m.weight.numpy(), w_before)

    def test_checkpoint_roundtrip(self):
        m = nn.Linear(4, 1)
        step = paddle.jit.TrainStep(m, nn.MSELoss(), paddle.optimizer.Adam(learning_rate=0.01))
        x, y = paddle.randn([8, 4]), paddle.randn([8, 1])
        step(x, y)
        state = step.state_for_checkpoint()
        l1 = float(step(x, y))
        step.restore_from_checkpoint(state)
        l2 = float(step(x, y))
        np.testing.assert_allclose(l1, l2, rtol=1e-6)


class TestAmp:
    def test_auto_cast_dtype(self):
        m = nn.Linear(8, 8)
        x = paddle.randn([2, 8])
        with paddle.amp.auto_cast(dtype="bfloat16"):
            out = m(x)
        assert "bfloat16" in str(out.dtype)
        out2 = m(x)
        assert "float32" in str(out2.dtype)

    def test_black_list_stays_fp32(self):
        x = paddle.randn([4, 4])
        with paddle.amp.auto_cast(dtype="bfloat16"):
            s = paddle.nn.functional.softmax(x)
        # softmax is in the black list → fp32 accumulation path
        assert np.isfinite(s.numpy()).all()

    def test_grad_scaler_scale_unscale(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0**10)
        w = paddle.to_tensor(np.array([1.0], dtype=np.float32), stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        loss = (w * w).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(w.numpy(), [0.8], rtol=1e-5)  # unscaled grad = 2

    def test_grad_scaler_skips_on_inf(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0**10)
        w = paddle.to_tensor(np.array([1.0], dtype=np.float32), stop_gradient=False)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
        loss = (w * float("inf")).sum()
        scaler.scale(loss).backward()
        scale_before = float(scaler._scale if hasattr(scaler, "_scale") else scaler.state_dict()["scale"])
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(w.numpy(), [1.0])  # step skipped
        scale_after = float(scaler._scale if hasattr(scaler, "_scale") else scaler.state_dict()["scale"])
        assert scale_after < scale_before


class TestSaveLoad:
    def test_paddle_save_load_state_dict(self):
        m = nn.Linear(4, 2)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "lin.pdparams")
            paddle.save(m.state_dict(), p)
            sd = paddle.load(p)
        m2 = nn.Linear(4, 2)
        m2.set_state_dict(sd)
        x = paddle.randn([2, 4])
        np.testing.assert_allclose(m(x).numpy(), m2(x).numpy(), rtol=1e-6)
