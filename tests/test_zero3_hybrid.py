"""ZeRO-3 on the flagship HybridTrainStep: params FSDP-shard over
('dp','sharding'), per-layer all-gather inside the scan, numerics unchanged.

Ref capability: fleet/meta_parallel/sharding/group_sharded_stage3.py (param
sharding + prefetch); here GSPMD inserts the gathers from the PartitionSpecs
in gpt_param_specs(zero_stage=3).
"""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import env as dist_env
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import HybridTrainStep


def _cfg(layers=2):
    return GPTConfig(vocab_size=256, hidden_size=64, num_layers=layers,
                     num_heads=4, max_seq_len=64, compute_dtype="float32",
                     use_flash=False)


def _ids(batch=8):
    return jnp.tile(jnp.arange(32, dtype=jnp.int32)[None, :] % 16, (batch, 1))


def _step(mesh, stage, seed=0):
    opt = paddle.optimizer.AdamW(
        1e-3, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    if mesh is not None:
        opt._shard_opt_states_axis = "sharding"
    return HybridTrainStep(_cfg(), opt, mesh=mesh, zero_stage=stage,
                           seed=seed)


def test_zero3_param_shards_quarter_bytes():
    """On dp2 x sharding2 each chip holds 1/4 of every FSDP-sharded block
    matrix (and of its fp32 Adam moments)."""
    mesh = dist_env.create_hybrid_mesh(dp=2, sharding=2, mp=2)
    step = _step(mesh, stage=3)
    loss = step(_ids())
    assert np.isfinite(float(np.asarray(jax.device_get(loss))))
    qkv = step.params["blocks"]["qkv_w"]
    spec = qkv.sharding.spec
    assert ("dp", "sharding") in tuple(spec), spec
    local = qkv.addressable_shards[0].data
    assert local.size * 8 == qkv.size, (local.shape, qkv.shape)  # /4 fsdp /2 mp
    # Adam moments follow the param sharding
    m = step.opt_state["slots"]["['blocks']['qkv_w']"]["moment1"] \
        if "['blocks']['qkv_w']" in step.opt_state["slots"] else None
    if m is None:  # name formatting differs; find by shape
        cand = [s["moment1"] for s in step.opt_state["slots"].values()
                if "moment1" in s and s["moment1"].shape == qkv.shape]
        m = cand[0]
    assert m.addressable_shards[0].data.size * 8 == m.size


def test_zero3_matches_zero1_numerics():
    """Sharding is a layout, not a math change: stage-3 losses track the
    stage-1 (replicated-param) trajectory."""
    mesh = dist_env.create_hybrid_mesh(dp=2, sharding=2, mp=2)
    ids = _ids()
    s3 = _step(mesh, stage=3, seed=5)
    s1 = _step(mesh, stage=1, seed=5)
    for _ in range(3):
        l3 = float(np.asarray(jax.device_get(s3(ids))))
        l1 = float(np.asarray(jax.device_get(s1(ids))))
    np.testing.assert_allclose(l3, l1, rtol=1e-5)


def test_zero3_compiled_arg_bytes_shrink():
    """The compiled executable's per-device argument residency drops when
    params shard (the memory-analysis proof, as in test_zero_gradaccum)."""
    mesh = dist_env.create_hybrid_mesh(dp=2, sharding=2, mp=2)
    ids = _ids()

    def compiled_arg_bytes(step):
        step(ids)  # builds + caches the jit
        lowered = step._jitted.lower(
            step._flat(step.params), step.opt_state, ids,
            jnp.asarray(1e-3, jnp.float32))
        mem = lowered.compile().memory_analysis()
        return None if mem is None else mem.argument_size_in_bytes

    b3 = compiled_arg_bytes(_step(mesh, stage=3))
    b1 = compiled_arg_bytes(_step(mesh, stage=1))
    if b3 is not None and b1 is not None:
        assert b3 < b1, (b3, b1)


def test_zero3_large_config_initializes_sharded():
    """A GPT config whose replicated fp32 params would be ~8x a single
    chip's share initializes with per-chip bytes = total/8 on an 8-way
    ('dp','sharding') product mesh — the capability that unlocks 6.7B+ on
    real pods (per-chip HBM is the binding constraint there)."""
    mesh = dist_env.create_hybrid_mesh(dp=2, sharding=4, mp=1)
    cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=4,
                    num_heads=8, max_seq_len=64, compute_dtype="float32",
                    use_flash=False)
    opt = paddle.optimizer.AdamW(1e-3)
    opt._shard_opt_states_axis = "sharding"
    step = HybridTrainStep(cfg, opt, mesh=mesh, zero_stage=3)
    qkv = step.params["blocks"]["qkv_w"]
    assert qkv.addressable_shards[0].data.size * 8 == qkv.size
    loss = step(_ids(8))
    assert np.isfinite(float(np.asarray(jax.device_get(loss))))
