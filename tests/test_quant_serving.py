"""Quantized serving (serving/quant.py + ops/pallas_kernels/quant_gemm.py):
int8/fp8 weight-only Pallas GEMMs + the quantized paged KV pool,
calibrated through the ``quantization`` package.

Gates:
  * flags-off (bf16/bf16) engine stays bitwise identical to
    generate_from_params — the unquantized contract is untouched;
  * the exactness contract at a GIVEN dtype config: a quantized engine is
    deterministic, admission-order invariant, and mp∈{2,4} quantized
    output is bitwise identical to single-chip QUANTIZED output on the
    gspmd/ring/fused rungs (scales shard with their channels);
  * logit drift vs the fp engine is bounded for every dtype config;
  * kill-and-resume on a quantized engine is bitwise vs an uninterrupted
    quantized run (greedy AND sampled, CheckpointManager round trip), and
    a dtype-mismatched restore raises the TYPED refusal naming both
    configs instead of deserializing garbage;
  * steady state keeps the static-executable discipline (paged_traces
    frozen after warmup at every dtype config);
  * calibration bridge: quantization.PTQ observers -> QuantSpec ->
    Engine/inference.serve, with up-front shape validation naming the
    offending leaf;
  * swap_params re-quantizes on device with zero retraces;
  * memory-equal capacity: an int8 engine built from the same KV byte
    budget holds ~4x the pages and serves beyond the fp engine's
    capacity, with kv_shard_bytes()/kv_bytes_per_token() reporting the
    quantized footprint.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu import serving
from paddle_tpu.models.generation import generate_from_params
from paddle_tpu.models.gpt import GPTConfig
from paddle_tpu.models.gpt_hybrid import init_gpt_params
from paddle_tpu.serving import metrics
from paddle_tpu.serving.quant import (
    QuantSpec, QuantSpecError, QuantDtypeMismatchError, calibrate,
    max_logit_drift,
)

# vocab 96 divides mp in {2, 4}: the quantized vocab-sharded lm head
# (head_w_s sharded over 'mp') is exercised, not just replicated
CFG = GPTConfig(vocab_size=96, hidden_size=64, num_layers=2, num_heads=4,
                max_seq_len=128, dropout=0.0, use_flash=False,
                compute_dtype="float32", remat=False)
_PARAMS = None


def _params():
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = init_gpt_params(CFG, jax.random.key(0))
    return _PARAMS


def _engine(**kw):
    kw.setdefault("num_slots", 3)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return serving.Engine(params=_params(), config=CFG, **kw)


_SHAPES = ((3, 4), (5, 6), (9, 4), (13, 6), (21, 5))


def _mixed_requests(n, rng, **kw):
    reqs = []
    for i in range(n):
        plen, mnt = _SHAPES[i % len(_SHAPES)]
        reqs.append(serving.Request(rng.integers(0, CFG.vocab_size, plen),
                                    max_new_tokens=mnt, **kw))
    return reqs


def _tok_lists(results, reqs):
    return [results[r.request_id].tokens for r in reqs]


# ---------------------------------------------------------------------------
# flags-off path untouched


def test_flags_default_bf16_and_bitwise_parity():
    """Defaults are bf16/bf16 (quant resolves to None) and the engine
    keeps the PR 13 bitwise contract with generate_from_params."""
    from paddle_tpu.flags import get_flags
    flags = get_flags()
    assert flags["FLAGS_serving_weight_dtype"] == "bf16"
    assert flags["FLAGS_serving_kv_dtype"] == "bf16"
    eng = _engine()
    assert eng._quant is None
    assert eng._kc.dtype == jnp.float32
    prompt = [1, 2, 3, 4, 5]
    res = eng.run([serving.Request(prompt, max_new_tokens=6)])
    ref = np.asarray(generate_from_params(
        _params(), np.asarray(prompt)[None], CFG,
        max_new_tokens=6)._data)[0, len(prompt):]
    assert list(res.values())[0].tokens == ref.tolist()


# ---------------------------------------------------------------------------
# exact-at-dtype-config contract


@pytest.mark.parametrize("dtype", ["int8", "fp8"])
def test_quant_engine_deterministic_and_order_invariant(dtype):
    """Same requests, two admission orders, two engines: identical token
    streams — the per-slot math is batch-independent at every dtype."""
    rng = np.random.default_rng(1)
    reqs_a = _mixed_requests(6, rng, do_sample=False)
    e1 = _engine(quant=dtype)
    out1 = _tok_lists(e1.run(reqs_a), reqs_a)

    rng = np.random.default_rng(1)
    reqs_b = _mixed_requests(6, rng, do_sample=False)
    e2 = _engine(quant=dtype)
    for r in reversed(reqs_b):                  # reversed submission order
        e2.submit(r)
    out2 = _tok_lists(e2.run(), reqs_b)
    assert out1 == out2


def test_quant_sampled_streams_deterministic():
    rng = np.random.default_rng(2)
    reqs = _mixed_requests(5, rng, do_sample=True, temperature=0.8,
                           top_p=0.9)
    states = [r.to_state() for r in reqs]
    out1 = _tok_lists(_engine(quant="int8").run(reqs), reqs)
    replay = [serving.Request.from_state(s) for s in states]
    out2 = _tok_lists(_engine(quant="int8").run(replay), replay)
    assert out1 == out2


@pytest.mark.parametrize("wd,kd", [("int8", "bf16"), ("bf16", "int8"),
                                   ("int8", "int8"), ("fp8", "fp8")])
def test_logit_drift_bounded_per_config(wd, kd):
    """Max |fp - quant| logit drift of a prefill forward stays a bounded
    fraction of the logit scale at every dtype config."""
    drift, scale = max_logit_drift(_params(), CFG, QuantSpec(wd, kd),
                                   list(range(1, 14)))
    assert drift > 0.0          # it IS quantized
    assert drift < 0.15 * max(scale, 1.0), (wd, kd, drift, scale)


def test_quant_vs_fp_greedy_tokens_mostly_agree():
    """Task-level drift: int8 weight+KV greedy streams agree with the fp
    engine on the (large) majority of tokens for this model."""
    rng = np.random.default_rng(3)
    reqs_fp = _mixed_requests(5, rng)
    fp = _tok_lists(_engine().run(reqs_fp), reqs_fp)
    rng = np.random.default_rng(3)
    reqs_q = _mixed_requests(5, rng)
    q = _tok_lists(_engine(quant="int8").run(reqs_q), reqs_q)
    total = sum(len(t) for t in fp)
    agree = sum(a == b for ft, qt in zip(fp, q) for a, b in zip(ft, qt))
    assert agree / total >= 0.6, (agree, total)


# ---------------------------------------------------------------------------
# mp: bitwise identical to single-chip QUANTIZED output


def _run_pair(quant, mp=None, comm_backend=None, sampled=True):
    rng = np.random.default_rng(4)
    kw = {}
    if mp is not None:
        kw.update(mp=mp, comm_backend=comm_backend)
    reqs = _mixed_requests(4, rng, do_sample=False) + _mixed_requests(
        2, np.random.default_rng(5), do_sample=sampled, temperature=0.7,
        top_p=0.95)
    eng = _engine(quant=quant, **kw)
    return _tok_lists(eng.run(reqs), reqs)


@pytest.mark.parametrize("mp,backend", [(2, None), (4, None), (2, "fused")])
def test_mp_quant_bitwise_vs_single_chip_quant(mp, backend):
    """The serving exactness contract at the int8 config: mp output ==
    single-chip QUANTIZED output bitwise, greedy AND sampled, on the
    default and fused rungs (scales shard with their channels; the fused
    rung dequantizes inside fused_gemm_ag's epilogue)."""
    single = _run_pair("int8")
    sharded = _run_pair("int8", mp=mp, comm_backend=backend)
    assert sharded == single


def test_mp_quant_fused_dispatches_quant_kernel():
    from paddle_tpu.ops.pallas_kernels import fused_collectives as fc
    before = fc.trace_counts().get("gemm_ag_q", 0)
    # num_slots=5 gives a dispatch shape no other test warms: the fused
    # quant kernel must trace HERE (builders/jit caches are process-wide)
    eng = _engine(quant="int8", mp=2, comm_backend="fused", num_slots=5)
    eng.run([serving.Request([1, 2, 3], max_new_tokens=2)])
    assert fc.trace_counts().get("gemm_ag_q", 0) > before
    # per-chip quantized KV bytes: 1/mp of the same-geometry int8 pool
    assert eng.kv_shard_bytes() * 2 == \
        _engine(quant="int8", num_slots=5).kv_shard_bytes()


# ---------------------------------------------------------------------------
# static-executable discipline at every dtype config


def test_quant_steady_state_trace_gate():
    """paged_traces freezes after warmup on the quantized engine: the
    scale operands are traced data, so admission/eviction/CoW/sampling
    changes never retrace (page_size=4 gives this config its own builder
    key — absolute counts are deterministic)."""
    eng = _engine(quant="int8", page_size=4, prefill_chunk=8)
    rng = np.random.default_rng(6)
    eng.run(_mixed_requests(4, rng))
    c = metrics.serving_counters()
    warm = c["paged_traces"]
    assert warm >= 2
    eng2 = _engine(quant="int8", page_size=4, prefill_chunk=8)
    eng2.run(_mixed_requests(6, np.random.default_rng(7),
                             do_sample=True, temperature=0.9))
    c2 = metrics.serving_counters()
    assert c2["paged_traces"] == warm    # a second engine adds ZERO traces


# ---------------------------------------------------------------------------
# snapshots: kill-and-resume bitwise + typed dtype refusal


@pytest.mark.parametrize("sampled", [False, True])
def test_quant_kill_and_resume_bitwise(tmp_path, sampled):
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    rng = np.random.default_rng(8)
    kw = dict(do_sample=sampled)
    if sampled:
        kw.update(temperature=0.8, top_p=0.9)
    reqs = _mixed_requests(5, rng, **kw)
    states = [r.to_state() for r in reqs]

    ref_eng = _engine(quant="int8")
    ref = _tok_lists(ref_eng.run(reqs), reqs)

    replay = [serving.Request.from_state(s) for s in states]
    eng = _engine(quant="int8")
    for r in replay:
        eng.submit(r)
    for _ in range(4):                      # mid-decode, mid-prefill
        eng.step()
    mgr = CheckpointManager(str(tmp_path), async_save=False,
                            keep_last_n=2)
    eng.attach_checkpoint(mgr, every=0)
    step = eng.save_snapshot(blocking=True)
    del eng

    fresh = _engine(quant="int8")
    state = mgr.restore(step)
    fresh.load_state_dict(state)
    results = fresh.run()
    got = [results[r.request_id].tokens for r in replay
           if r.request_id in results]
    # every request resolves and matches the uninterrupted quantized run
    assert len(got) == len(replay)
    assert got == ref, f"sampled={sampled}"


def test_fp8_snapshot_roundtrip_and_run(tmp_path):
    """fp8 pools snapshot as raw bytes (numpy IO paths don't all speak
    ml_dtypes) and restore bit-exact through CheckpointManager."""
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    eng = _engine(quant="fp8")
    eng.submit(serving.Request([1, 2, 3, 4, 5, 6, 7, 8, 9],
                               max_new_tokens=5))
    for _ in range(3):
        eng.step()
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    eng.attach_checkpoint(mgr, every=0)
    step = eng.save_snapshot(blocking=True)
    ref = {k: v.tokens for k, v in eng.run().items()}

    fresh = _engine(quant="fp8")
    fresh.load_state_dict(mgr.restore(step))
    assert fresh._kc.dtype == jnp.float8_e4m3fn
    got = {k: v.tokens for k, v in fresh.run().items()}
    assert got == ref


def test_dtype_mismatched_restore_refused():
    """Restoring an int8 snapshot into a bf16 engine (or any other dtype
    mix) raises the TYPED refusal naming both configs — never
    deserializes garbage KV bytes."""
    qeng = _engine(quant="int8")
    qeng.submit(serving.Request([1, 2, 3], max_new_tokens=3))
    qeng.step()
    snap = qeng.state_dict()

    fp = _engine()
    with pytest.raises(QuantDtypeMismatchError) as ei:
        fp.load_state_dict(snap)
    msg = str(ei.value)
    assert "int8" in msg and "bf16" in msg
    assert ei.value.snapshot_config == ("int8", "int8")
    assert ei.value.engine_config == ("bf16", "bf16")

    # and the reverse: an fp snapshot into a quantized engine
    fp2 = _engine()
    fp2.submit(serving.Request([1, 2, 3], max_new_tokens=3))
    fp2.step()
    with pytest.raises(QuantDtypeMismatchError):
        _engine(quant="int8").load_state_dict(fp2.state_dict())
    # fp8 != int8 is a mismatch too
    with pytest.raises(QuantDtypeMismatchError):
        _engine(quant="fp8").load_state_dict(snap)


# ---------------------------------------------------------------------------
# calibration bridge + validation


def test_calibrate_produces_accepted_spec():
    spec = calibrate(_params(), CFG, sample_ids=list(range(1, 33)))
    assert spec.weight_dtype == "int8" and spec.kv_dtype == "int8"
    ws = spec.weight_scales
    assert set(ws["blocks"]) == {"qkv_w", "out_w", "up_w", "down_w"}
    assert ws["blocks"]["qkv_w"].shape == (CFG.num_layers,
                                           3 * CFG.hidden_size)
    assert ws["head_w"].shape == (CFG.vocab_size,)
    assert spec.kv_k_clip.shape == (CFG.num_layers,)
    assert (spec.kv_k_clip > 0).all() and (spec.kv_v_clip > 0).all()
    eng = _engine(quant=spec)
    res = eng.run([serving.Request([1, 2, 3, 4], max_new_tokens=4)])
    assert list(res.values())[0].tokens
    # a calibrated engine is deterministic vs itself
    res2 = _engine(quant=spec).run(
        [serving.Request([1, 2, 3, 4], max_new_tokens=4)])
    assert [r.tokens for r in res.values()] == \
        [r.tokens for r in res2.values()]


def test_calibrate_with_percentile_observer():
    from paddle_tpu.quantization import PercentileObserver
    spec = calibrate(_params(), CFG, sample_ids=list(range(1, 33)),
                     kv_observer=lambda: PercentileObserver(99.0))
    absmax = calibrate(_params(), CFG, sample_ids=list(range(1, 33)))
    # percentile clips the tail: ranges never exceed absmax ranges
    assert (spec.kv_k_clip <= absmax.kv_k_clip + 1e-12).all()
    assert _engine(quant=spec).run(
        [serving.Request([5, 6, 7], max_new_tokens=3)])


def test_spec_shape_validation_names_leaf():
    spec = calibrate(_params(), CFG, sample_ids=list(range(1, 17)))
    bad = {"blocks": dict(spec.weight_scales["blocks"]),
           "head_w": spec.weight_scales["head_w"]}
    bad["blocks"]["up_w"] = np.ones((CFG.num_layers, 3), np.float32)
    with pytest.raises(QuantSpecError, match="up_w"):
        _engine(quant=QuantSpec("int8", "int8", weight_scales=bad,
                                kv_k_clip=spec.kv_k_clip,
                                kv_v_clip=spec.kv_v_clip))
    # unknown leaf named too
    bad2 = {"blocks": dict(spec.weight_scales["blocks"]),
            "head_w": spec.weight_scales["head_w"], "wte": np.ones(4)}
    with pytest.raises(QuantSpecError, match="wte"):
        _engine(quant=QuantSpec("int8", "bf16", weight_scales=bad2))
    # wrong kv clip length named
    with pytest.raises(QuantSpecError, match="kv_k_clip"):
        _engine(quant=QuantSpec("bf16", "int8",
                                kv_k_clip=np.ones(7), kv_v_clip=np.ones(7)))
    # bad dtype string
    with pytest.raises(QuantSpecError, match="int4"):
        _engine(quant="int4")


def test_inference_serve_accepts_spec_and_rejects_bad():
    from paddle_tpu import inference
    spec = calibrate(_params(), CFG, sample_ids=list(range(1, 17)))
    eng = inference.serve(params=_params(), config=CFG, quant=spec,
                          num_slots=2, max_seq_len=64, page_size=8,
                          prefill_chunk=8)
    assert eng._quant is not None and eng._kc.dtype == jnp.int8
    bad = {"blocks": {k: np.ones((1, 1), np.float32)
                      for k in ("qkv_w", "out_w", "up_w", "down_w")},
           "head_w": np.ones(2, np.float32)}
    with pytest.raises(QuantSpecError, match="qkv_w"):
        inference.serve(params=_params(), config=CFG,
                        quant=QuantSpec("int8", "bf16", weight_scales=bad))


# ---------------------------------------------------------------------------
# hot weight swap: re-quantize on device, zero retraces


def test_swap_params_requantizes_zero_retraces():
    eng = _engine(quant="int8", page_size=4, prefill_chunk=4)
    eng.run([serving.Request([1, 2, 3, 4, 5], max_new_tokens=4)])
    traces = metrics.serving_counters()["paged_traces"]
    new_fp = init_gpt_params(CFG, jax.random.key(9))
    eng.swap_params(new_fp, version=2)
    assert eng.params["blocks"]["qkv_w"].dtype == jnp.int8
    res = eng.run([serving.Request([1, 2, 3, 4, 5], max_new_tokens=4)])
    assert metrics.serving_counters()["paged_traces"] == traces
    # requantization is deterministic: a fresh engine built on the new
    # weights produces the same stream
    fresh = serving.Engine(params=new_fp, config=CFG, quant="int8",
                           num_slots=3, max_seq_len=96, page_size=4,
                           prefill_chunk=4)
    res2 = fresh.run([serving.Request([1, 2, 3, 4, 5], max_new_tokens=4)])
    assert [r.tokens for r in res.values()] == \
        [r.tokens for r in res2.values()]


# ---------------------------------------------------------------------------
# fleet integration: supervisor respawn on quantized engines


def test_supervisor_kill_respawn_quantized_zero_dropped(tmp_path):
    """A replica kill on a fleet of QUANTIZED engines: the supervisor
    respawns from the last cadence snapshot (dtype config matches the
    factory's, so the typed refusal never fires) and every request
    resolves with the tokens an unkilled quantized engine produces —
    zero drops, exact at the dtype config."""
    from paddle_tpu import profiler
    from paddle_tpu.serving.supervisor import ServingSupervisor
    from paddle_tpu.utils import fault_injection as fi

    def factory():
        return _engine(quant="int8", num_slots=3)

    def traffic(seed):
        rng = np.random.default_rng(seed)
        return [serving.Request(rng.integers(0, CFG.vocab_size, 5 + 2 * i),
                                max_new_tokens=4 + (i % 3), seed=i)
                for i in range(6)]

    golden_reqs = traffic(21)
    golden = {r.request_id: t for r, t in zip(
        golden_reqs,
        _tok_lists(_engine(quant="int8", num_slots=3,
                           max_queue=16).run(golden_reqs), golden_reqs))}

    profiler.reset_serving_counters()
    reqs = traffic(21)
    id_map = dict(zip((r.request_id for r in reqs),
                      (r.request_id for r in golden_reqs)))
    sup = ServingSupervisor(factory, num_replicas=2,
                            snapshot_dir=str(tmp_path), snapshot_every=2)
    with fi.inject(fi.FaultPlan(kill_at_decode_step=3,
                                kill_engine_tag="replica0")):
        results = sup.run(reqs)
        assert fi.stats()["serving_kills"] == 1
    assert len(results) == len(reqs)
    for r in reqs:
        assert results[r.request_id].tokens == golden[id_map[r.request_id]]
    c = profiler.serving_counters()
    assert c["dropped"] == 0 and c["respawns"] >= 1


# ---------------------------------------------------------------------------
# prefix sharing / CoW on quantized pages


def test_quant_prefix_sharing_and_cow_divergence():
    """Prefix-shared siblings on a quantized pool: same prefix pages
    (quantized bytes + scales shared), divergent continuations stay
    independent, everything deterministic vs an unshared run."""
    base = list(range(1, 17))                   # two full pages at ps=8
    r1 = serving.Request(base + [20], max_new_tokens=4, seed=1)
    r2 = serving.Request(base + [30], max_new_tokens=4, seed=2)
    eng = _engine(quant="int8")
    eng.submit(r1)
    out1 = eng.run()
    eng.submit(r2)                              # prefix-hits r1's pages
    out2 = eng.run()
    hits = metrics.serving_counters()["prefix_hits"]

    solo = _engine(quant="int8", prefix_cache=False)
    s1 = solo.run([serving.Request(base + [20], max_new_tokens=4, seed=1)])
    s2 = solo.run([serving.Request(base + [30], max_new_tokens=4, seed=2)])
    assert list(out1.values())[0].tokens == list(s1.values())[0].tokens
    assert list(out2.values())[0].tokens == list(s2.values())[0].tokens
    assert hits >= 1
    bal = eng.pool.balance()
    assert bal["conserved"] and bal["refcounts_accounted"]


# ---------------------------------------------------------------------------
# memory-equal capacity + metrics


def test_memory_equal_capacity_and_dtype_bytes():
    """Same KV byte budget: the int8 pool holds 4x the fp32 pages, admits
    beyond the fp engine's page capacity, and the byte gauges report the
    quantized footprint."""
    fp = _engine(num_pages=12, num_slots=2)          # 11 usable pages
    q = _engine(num_pages=48, num_slots=2, quant="int8")
    assert q.kv_shard_bytes() <= fp.kv_shard_bytes()
    assert q.kv_bytes_per_token() * 3 < fp.kv_bytes_per_token()
    # 11 usable pages * ps 8 = 88 positions: a whole-lifetime 96-token
    # request can never fit the fp pool but fits the int8 pool
    big = lambda seed: serving.Request(
        np.random.default_rng(seed).integers(0, CFG.vocab_size, 60),
        max_new_tokens=36)
    with pytest.raises(ValueError):
        fp.submit(big(1))
    res = q.run([big(1)])
    assert len(list(res.values())[0].tokens) == 36
    c = metrics.serving_counters()
    assert c["quant_kv_bytes_per_token"] == q.kv_bytes_per_token()
    assert c["quant_scale_bytes"] > 0


def test_quant_summary_and_registry_visible():
    _engine(quant="int8").run(
        [serving.Request([1, 2, 3], max_new_tokens=2)])
    s = serving.serving_summary()
    assert "quant: w=int8 kv=int8" in s
    from paddle_tpu.observability.registry import REGISTRY
    snap = REGISTRY.snapshot()
    keys = {k for k in snap if "quant" in k}
    assert any("quant_scale_bytes" in k for k in keys)
    assert any("quant_kv_bytes_per_token" in k for k in keys)


# ---------------------------------------------------------------------------
# kernels


def test_quant_gemm_kernel_interpret_parity():
    from paddle_tpu.ops.pallas_kernels.quant_gemm import (
        quant_gemm, quant_gemm_kernel, quant_gemm_supported)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((8, 256)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((256, 256)), jnp.float32)
    from paddle_tpu.serving.quant import _quantize_leaf
    wq, s = _quantize_leaf(w, "int8")
    ref = quant_gemm(x, wq, s)                       # jnp epilogue
    got = quant_gemm_kernel(x, wq, s, interpret=True)
    # the kernel's k-tiled fp32 accumulation reorders the contraction
    # sum vs the one-shot jnp matmul: numerically equivalent, not
    # bitwise (the kernel is TPU-routed, never part of a bitwise gate)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-3, atol=1e-4)
    assert not quant_gemm_supported(8, 256, 256)     # CPU backend
    assert not quant_gemm_supported(8, 100, 256)


def test_paged_decode_kernel_quant_interpret_parity():
    """The quantized Pallas paged-decode kernel (dequant inside the
    online-softmax loop) matches the jnp gather read on a quantized
    pool."""
    from paddle_tpu.serving.paged_attention import (
        paged_attention_read, paged_decode_attention_q)
    rng = np.random.default_rng(12)
    B, nh, d, ps, P, MP = 2, 4, 16, 8, 9, 3
    q = jnp.asarray(rng.standard_normal((B, 1, nh, d)), jnp.float32)
    kq = jnp.asarray(rng.integers(-127, 128, (P, ps, nh, d)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (P, ps, nh, d)), jnp.int8)
    table = jnp.asarray([[1, 2, 3], [4, 5, 0]], jnp.int32)
    pos = jnp.asarray([[17], [9]], jnp.int32)
    ksc = jnp.asarray(rng.uniform(0.01, 0.1, P), jnp.float32)
    vsc = jnp.asarray(rng.uniform(0.01, 0.1, P), jnp.float32)
    ref = paged_attention_read(q, kq, vq, table, pos, ps, False,
                               jnp.float32, ksc, vsc)
    got = paged_decode_attention_q(q[:, 0], kq, vq, table, pos[:, 0],
                                   ksc, vsc, page_size=ps,
                                   interpret=True)[:, None]
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# smoke sub-rung (fast deterministic; throughput/drift gates are slow)


def _load_smoke():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "tools_serving_smoke", "tools_serving_smoke.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_smoke_quant_deterministic_subrung():
    """tools_serving_smoke --quant in deterministic tiny mode: the
    memory-EQUAL int8 engine holds strictly more pages/slots from the
    same byte budget, outputs are deterministic, and max logit drift is
    bounded — no wall-clock gates (slow rung below)."""
    mod = _load_smoke()
    out = mod.run_quant_rung(quick=True, deterministic=True)
    assert out["quant"]["kv_pool_bytes"] <= out["fp"]["kv_pool_bytes"]
    assert out["quant"]["pages"] > out["fp"]["pages"]
    assert out["quant"]["slots"] >= out["fp"]["slots"]
    assert out["capacity_only_quant"]
    assert out["max_logit_drift"] < 0.15 * max(out["max_abs_logit"], 1.0)
    assert out["greedy_agreement"] >= 0.5


@pytest.mark.slow
def test_smoke_quant_memory_equal_gate():
    """Full memory-equal rung: slots x tokens/s strictly UP under int8
    weights + int8 KV from the same HBM budget, drift bounded."""
    mod = _load_smoke()
    out = mod.run_quant_rung(quick=False, deterministic=False)
    assert out["quant"]["capacity_throughput"] > \
        out["fp"]["capacity_throughput"]
    assert out["max_logit_drift"] < 0.15 * max(out["max_abs_logit"], 1.0)
