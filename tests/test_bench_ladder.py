"""Forced-failure tests of bench.py's config ladder + progressive emission.

Round-2 lesson: the pallas kernel failed to lower on TPU and the bench
recorded 0.0 even though the working blockwise XLA path existed. The ladder
must walk flash -> blockwise within a config and report which path ran.

Round-4 lesson (rc=124, no JSON line): the ladder now runs SMALLEST config
first and emits a full result line after EVERY success, so a driver timeout
mid-run still leaves captured TPU evidence, and the jax-free parent prints
the best-so-far from a SIGTERM handler.
"""
import io
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def _runner_factory(fail_pred, record):
    def runner(model, batch, seq, use_flash):
        record.append((model, batch, seq, use_flash))
        if fail_pred(model, batch, seq, use_flash):
            raise RuntimeError(f"forced failure {model} bs={batch} "
                               f"flash={use_flash}")
        # mfu grows with model size so "best" == biggest successful config
        size = {"gpt3-345M": 0.3, "gpt3-760M": 0.4, "gpt3-1.3B": 0.5,
                "gpt3-2.7B": 0.35}[model]  # offloaded moments: capability, not peak MFU
        return {"metric": "x", "value": 1000.0 * size, "unit": "tokens/s/chip",
                "vs_baseline": size / 0.45, "mfu": size,
                "attention": "pallas" if use_flash else "blockwise",
                "model": model, "batch": batch, "backend": "tpu"}
    return runner


def test_groups_smallest_first_flash_first():
    groups = bench.build_groups(on_tpu=True)
    assert groups[0][0][0] == "gpt3-345M"  # smallest config leads
    for group in groups:
        assert group[0][3] is True  # pallas preferred within each group
    # monotone non-decreasing model scale down the ladder
    order = ["gpt3-345M", "gpt3-760M", "gpt3-1.3B", "gpt3-2.7B"]
    idx = [order.index(g[0][0]) for g in groups]
    assert idx == sorted(idx)


def test_happy_path_emits_every_group_and_returns_best():
    groups = bench.build_groups(on_tpu=True)
    rec, emitted = [], []
    out = bench.run_groups(groups, _runner_factory(lambda *a: False, rec),
                           emitted.append)
    # one success per distinct group, all emitted progressively
    assert len(emitted) == len(groups)
    assert emitted[0]["model"] == "gpt3-345M"  # first evidence is smallest
    assert out["model"] == "gpt3-1.3B" and out["mfu"] == 0.5  # best wins


def test_flash_failure_falls_back_to_blockwise_within_group():
    """The round-2 scenario: every flash config dies at lowering."""
    groups = bench.build_groups(on_tpu=True)
    rec, emitted = [], []
    out = bench.run_groups(
        groups, _runner_factory(lambda m, b, s, f: f, rec), emitted.append)
    assert out["attention"] == "blockwise"
    assert out["value"] > 0
    assert all(r["attention"] == "blockwise" for r in emitted)


def test_big_config_oom_keeps_small_config_evidence():
    """Round-3 scenario inverted: 1.3B OOMs, but the 345M/760M lines were
    already emitted — the round keeps its evidence."""
    groups = bench.build_groups(on_tpu=True)
    rec, emitted = [], []
    out = bench.run_groups(
        groups,
        _runner_factory(lambda m, b, s, f: m in ("gpt3-1.3B", "gpt3-2.7B"),
                        rec),
        emitted.append)
    assert {r["model"] for r in emitted} == {"gpt3-345M", "gpt3-760M"}
    assert out["model"] == "gpt3-760M"


def test_total_failure_still_returns_json_shape():
    groups = bench.build_groups(on_tpu=True)
    out = bench.run_groups(groups, _runner_factory(lambda *a: True, []),
                           lambda r: None)
    assert out["value"] == 0.0 and "error" in out
    assert out["unit"] == "tokens/s/chip"


def test_every_tpu_config_has_blockwise_fallback():
    for group in bench.build_groups(on_tpu=True):
        flash = {(m, b, s) for m, b, s, f in group if f}
        blockwise = {(m, b, s) for m, b, s, f in group if not f}
        assert flash == blockwise


def test_best_of_picks_highest_mfu():
    rs = [{"mfu": 0.3, "value": 1.0}, {"mfu": 0.5, "value": 2.0},
          {"mfu": 0.4, "value": 9.0}]
    assert bench._best_of(rs)["mfu"] == 0.5


def test_parent_emit_best_reads_results_file(tmp_path, capsys):
    p = bench._Parent()
    with open(p.results_path, "w") as f:
        f.write(json.dumps({"metric": "a", "value": 1.0, "mfu": 0.2,
                            "unit": "tokens/s/chip", "vs_baseline": 0.4}) + "\n")
        f.write("garbage not json\n")
        f.write(json.dumps({"metric": "b", "value": 2.0, "mfu": 0.5,
                            "unit": "tokens/s/chip", "vs_baseline": 1.1}) + "\n")
    p.emit_best()
    p.emit_best()  # idempotent: exactly one line total
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    assert json.loads(out[0])["metric"] == "b"
    os.unlink(p.results_path)


def test_parent_emit_best_empty_results_is_error_line(capsys):
    p = bench._Parent()
    p.emit_best(note="x")
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 0.0 and "error" in out and out["note"] == "x"
    os.unlink(p.results_path)


def test_sigterm_mid_run_prints_best_so_far(tmp_path):
    """Integration: drive bench.py's parent with a stub child that emits one
    result then sleeps forever; SIGTERM the parent (the driver-timeout path)
    and require the captured result on stdout."""
    stub = tmp_path / "stub_bench.py"
    repo_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sentinel = tmp_path / "child_wrote.flag"
    stub.write_text(f"""
import json, sys, time
if len(sys.argv) >= 2 and sys.argv[1] == "--child":
    with open(sys.argv[3], "a") as f:
        f.write(json.dumps({{"metric": "stub", "value": 42.0, "mfu": 0.5,
                             "unit": "tokens/s/chip", "vs_baseline": 1.1,
                             "backend": "tpu"}}) + "\\n")
    open({str(sentinel)!r}, "w").write("ok")
    time.sleep(600)  # hang like a wedged bigger-config attempt
    sys.exit(0)
sys.path.insert(0, {repo_dir!r})
import bench
bench.__file__ = __file__  # parent must relaunch THIS stub as the child
bench.main()
""")
    # budget must exceed the sentinel-poll window below, or a slow child
    # lets the parent hit its own deadline and emit without the note
    env = dict(os.environ, BENCH_TOTAL_BUDGET_S="300")
    proc = subprocess.Popen([sys.executable, str(stub)],
                            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                            env=env, text=True)
    # wait until the child has actually written its result line (fixed
    # sleeps flake when the sandbox is under load), then a little more for
    # the parent's signal handler installation
    for _ in range(120):
        if sentinel.exists():
            break
        time.sleep(1.0)
    else:
        proc.kill()
        raise AssertionError("stub child never wrote its result line")
    time.sleep(3.0)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=30)
    line = json.loads(out.strip().splitlines()[-1])
    assert line["value"] == 42.0
    assert "note" in line  # flagged as signal-handler emission
