"""Forced-failure tests of bench.py's attention fallback ladder.

Round-2 lesson: the pallas kernel failed to lower on TPU and the bench
recorded 0.0 even though the working blockwise XLA path existed. The ladder
must walk flash -> blockwise -> smaller configs and report which path ran.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench


def _runner_factory(fail_pred, record):
    def runner(model, batch, seq, use_flash):
        record.append((model, batch, seq, use_flash))
        if fail_pred(model, batch, seq, use_flash):
            raise RuntimeError(f"forced failure {model} bs={batch} "
                               f"flash={use_flash}")
        return {"metric": "x", "value": 1.0, "unit": "tokens/s/chip",
                "vs_baseline": 0.5,
                "attention": "pallas" if use_flash else "blockwise",
                "model": model, "batch": batch}
    return runner


def test_ladder_happy_path_uses_flash_first():
    attempts = bench.build_attempts(on_tpu=True)
    assert attempts[0][3] is True  # pallas first
    rec = []
    out = bench.run_ladder(attempts, _runner_factory(lambda *a: False, rec))
    assert out["attention"] == "pallas"
    assert len(rec) == 1


def test_ladder_falls_back_to_blockwise_on_kernel_failure():
    """The round-2 scenario: every flash config dies at lowering. The ladder
    must recover with the blockwise path on the SAME (model, bs) config."""
    attempts = bench.build_attempts(on_tpu=True)
    rec = []
    out = bench.run_ladder(
        attempts, _runner_factory(lambda m, b, s, f: f, rec))
    assert out["attention"] == "blockwise"
    assert out["value"] > 0
    # fell back within the top config, not all the way down the ladder
    assert out["model"] == attempts[0][0] and out["batch"] == attempts[0][1]


def test_ladder_oom_walks_to_smaller_batch():
    attempts = bench.build_attempts(on_tpu=True)
    big = attempts[0][1]
    rec = []
    out = bench.run_ladder(
        attempts, _runner_factory(lambda m, b, s, f: b == big, rec))
    assert out["value"] > 0 and out["batch"] < big


def test_ladder_total_failure_still_emits_json_shape():
    attempts = bench.build_attempts(on_tpu=True)
    out = bench.run_ladder(attempts, _runner_factory(lambda *a: True, []))
    assert out["value"] == 0.0 and "error" in out
    assert out["unit"] == "tokens/s/chip"


def test_every_tpu_config_has_blockwise_fallback():
    attempts = bench.build_attempts(on_tpu=True)
    flash_cfgs = {(m, b, s) for m, b, s, f in attempts if f}
    blockwise_cfgs = {(m, b, s) for m, b, s, f in attempts if not f}
    assert flash_cfgs == blockwise_cfgs
