"""End-to-end mini pretraining: hybrid train step on a mesh -> loss
drops -> checkpoint -> exact resume -> generation from the trained
weights. Ties the flagship pieces together the way a user would."""
import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.distributed import env as dist_env
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.models.gpt_hybrid import HybridTrainStep


def _cfg():
    return GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=64, compute_dtype="float32",
                     use_flash=False, remat=False)


def _batch(step_idx=0):
    # a memorizable pattern: ids cycle with period 8
    rng = np.random.default_rng(step_idx % 4)
    start = rng.integers(0, 8, size=(8, 1))
    ids = (start + np.arange(32)[None, :]) % 8
    return jnp.asarray(ids, jnp.int32)


def test_pretrain_checkpoint_resume_generate(tmp_path):
    mesh = dist_env.create_hybrid_mesh(dp=2, mp=2, pp=2)
    cfg = _cfg()
    opt = paddle.optimizer.AdamW(
        5e-3, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    step = HybridTrainStep(cfg, opt, mesh=mesh, num_microbatches=2)

    first = float(np.asarray(jax.device_get(step(_batch(0)))))
    for i in range(1, 12):
        loss = step(_batch(i))
    mid = float(np.asarray(jax.device_get(loss)))
    assert mid < first, (first, mid)

    # checkpoint -> keep training 3 steps -> restore -> the SAME 3 steps
    # reproduce bit-identical losses (exact resume)
    snap = jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)),
        (step._flat(step.params), step.opt_state))
    after = [float(np.asarray(jax.device_get(step(_batch(12 + i)))))
             for i in range(3)]
    flat, opt_state = snap
    step.params = step._unflat(
        {k: jnp.asarray(v) for k, v in flat.items()})
    step.opt_state = jax.tree_util.tree_map(jnp.asarray, opt_state)
    if step.mesh is not None:
        step._place()
    replay = [float(np.asarray(jax.device_get(step(_batch(12 + i)))))
              for i in range(3)]
    np.testing.assert_allclose(replay, after, rtol=1e-6)

    # load trained weights into the Layer model and generate: the model
    # should continue the period-8 pattern better than chance
    model = GPTForCausalLM(cfg)
    model.eval()
    trained = jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)), step.params)
    blocks = trained["blocks"]
    # map functional params back onto the Layer weights
    gpt = model.gpt
    gpt.wte.weight._data = jnp.asarray(trained["wte"])
    gpt.wpe.weight._data = jnp.asarray(trained["wpe"])
    gpt.ln_f.weight._data = jnp.asarray(trained["lnf_g"])
    gpt.ln_f.bias._data = jnp.asarray(trained["lnf_b"])
    model.lm_head.weight._data = jnp.asarray(trained["head_w"])
    name_map = {
        "ln1_g": lambda b: b.ln_1.weight, "ln1_b": lambda b: b.ln_1.bias,
        "qkv_w": lambda b: b.attn.qkv_proj.weight,
        "qkv_b": lambda b: b.attn.qkv_proj.bias,
        "out_w": lambda b: b.attn.out_proj.weight,
        "out_b": lambda b: b.attn.out_proj.bias,
        "ln2_g": lambda b: b.ln_2.weight, "ln2_b": lambda b: b.ln_2.bias,
        "up_w": lambda b: b.mlp.up_proj.weight,
        "up_b": lambda b: b.mlp.up_proj.bias,
        "down_w": lambda b: b.mlp.down_proj.weight,
        "down_b": lambda b: b.mlp.down_proj.bias,
    }
    for key, get in name_map.items():
        stacked = blocks[key]
        for li, blk in enumerate(model.gpt.h):
            get(blk)._data = jnp.asarray(stacked[li])

    prompt = np.asarray([[0, 1, 2, 3]], np.int64)
    out = np.asarray(model.generate(paddle.to_tensor(prompt),
                                    max_new_tokens=8).numpy())[0]
    want = np.arange(4, 12) % 8
    acc = (out[4:] == want).mean()
    assert acc >= 0.5, (out, want, acc)
