#!/usr/bin/env python
"""Headline benchmark: GPT-3-class pretraining throughput on one TPU chip.

Metric (BASELINE.json): tokens/sec/chip + MFU for GPT-3 1.3B-13B.
Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": "tokens/s/chip",
   "vs_baseline": mfu / 0.45, ...}
vs_baseline compares achieved MFU against the 45% north-star (BASELINE.json).

Architecture (round-5 rework — four rounds of TPU evidence were lost to
tunnel outages / timeouts):

  parent (this process, NEVER imports jax, so it can always handle signals)
    └─ TPU child: runs the config ladder SMALLEST FIRST, appending one full
       result JSON line to a results file after EVERY successful config.
       The first line lands within one small-config compile (warm
       .jax_cache: ~2 min), then bigger configs upgrade it in place.
    └─ CPU child: tiny config, only if the TPU child produced nothing.

The parent prints the best captured result (highest MFU) exactly once: at
normal completion, at its own deadline (BENCH_TOTAL_BUDGET_S, default 1680s
— inside the driver's 30-min cap), or from a SIGTERM/SIGINT handler if the
driver kills it first. A TPU child that hangs claiming the chip is orphaned,
never killed (killing mid-claim wedges the tunnel for the next client).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_T0 = time.time()


def _log(msg):
    sys.stderr.write(f"[bench +{time.time() - _T0:7.1f}s] {msg}\n")
    sys.stderr.flush()


# FLOP/MFU estimators live in paddle_tpu/observability/flops.py — the
# SINGLE source shared with tools_mfu_sweep.py and the live step
# telemetry, so the bench trajectory and in-run MFU can never diverge.
# Delegated lazily: the parent process must stay import-light (importing
# paddle_tpu pulls jax), and only the children call these.

def peak_flops_bf16(device_kind: str) -> float:
    from paddle_tpu.observability.flops import peak_flops_bf16 as f
    return f(device_kind)


def model_flops_per_token(cfg, seq_len):
    """6N matmul + attention term (per training token, fwd+bwd)."""
    from paddle_tpu.observability.flops import model_flops_per_token as f
    return f(cfg, seq_len)


# --------------------------------------------------------------------------
# child side: actually runs configs (imports jax)
# --------------------------------------------------------------------------

def run(model_name, batch, seq, steps=10, warmup=2, use_flash=True):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT_CONFIGS
    from paddle_tpu.models.gpt_hybrid import HybridTrainStep

    cfg = GPT_CONFIGS[model_name]
    cfg.max_seq_len = max(cfg.max_seq_len, seq)
    on_tpu = jax.default_backend() == "tpu"
    cfg.use_flash = use_flash and on_tpu
    cfg.compute_dtype = "bfloat16" if on_tpu else "float32"
    cfg.remat = True

    # bf16 params; moments drop to bf16 storage when fp32 moments alone would
    # crowd a 16G chip (>= ~1B params: 2 + 8 bytes/param > half of HBM). The
    # measured alternative is a guaranteed compile-time HBM OOM ("Used 20.4G
    # of 15.75G") — bf16 moments are the single-chip analog of the
    # reference's ZeRO moment sharding across a GPU pod.
    _, n_params = model_flops_per_token(cfg, seq)
    moment_dtype = "bfloat16" if (on_tpu and n_params > 1.0e9) else "float32"
    # 2.7B+: even bf16 moments + bf16 params exceed 16G HBM — stream the
    # moments from pinned host memory instead (fleet stage-3 offload analog)
    offload = bool(on_tpu and n_params > 2.0e9)
    opt = paddle.optimizer.AdamW(2e-4, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
                                 moment_dtype=moment_dtype)
    param_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    _log(f"{model_name} bs={batch} seq={seq}: init params"
         f"{' (moments offloaded to host)' if offload else ''}...")
    step = HybridTrainStep(cfg, opt, param_dtype=param_dtype, offload=offload)
    key = jax.random.key(0)
    ids = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size, jnp.int32)

    _log("warmup (includes XLA compile)...")
    for _ in range(warmup):
        loss = step(ids)
    # device_get, NOT block_until_ready: the axon remote platform's
    # block_until_ready returns before remote execution finishes (measured:
    # "6000 TFLOP/s" on a 197-TFLOP chip). Fetching the scalar forces a
    # genuine round-trip sync and costs only the scalar transfer.
    jax.device_get(loss)
    _log("timed steps...")
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids)
    jax.device_get(loss)
    dt = (time.perf_counter() - t0) / steps
    tokens_per_sec = batch * seq / dt
    fpt, n_params = model_flops_per_token(cfg, seq)
    dev = jax.devices()[0]
    peak = peak_flops_bf16(getattr(dev, "device_kind", "unknown"))
    mfu = tokens_per_sec * fpt / peak
    attn = "pallas" if cfg.use_flash else "blockwise"
    import numpy as np
    return {
        "metric": f"GPT pretrain tokens/sec/chip ({model_name}, seq={seq}, "
                  f"bs={batch}, bf16+remat+attn={attn}, 1 chip)",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "step_time_s": round(dt, 4),
        "loss": float(np.asarray(jax.device_get(loss))),
        "n_params": n_params,
        "attention": attn,
        "device": getattr(dev, "device_kind", str(dev)),
        "backend": jax.default_backend(),
        "peak_flops_assumed": peak,
    }


def build_groups(on_tpu):
    """Config ladder, SMALLEST FIRST so the first result line lands fast.

    Each group is one (model, batch, seq) config with its attention
    variants in preference order: pallas flash first, then the blockwise
    XLA attention (a kernel regression must never zero the round's perf
    evidence — round-2 lesson). Within a group the first success wins and
    the child moves on to the next (bigger) group to upgrade the result.
    """
    if not on_tpu:
        return [[("gpt3-125M", 2, 256, False)]]
    groups = []
    for model_name, batches, seq in [("gpt3-345M", [8], 2048),
                                     ("gpt3-760M", [8], 2048),
                                     # bs4 is an OOM fallback variant of the
                                     # same group, not a separate group — a
                                     # bs8 success must not burn budget on it
                                     ("gpt3-1.3B", [8, 4], 2048),
                                     # stretch: host-offloaded moments (run()
                                     # auto-enables offload > 2e9 params)
                                     ("gpt3-2.7B", [4], 2048)]:
        group = []
        for batch in batches:
            group.append((model_name, batch, seq, True))   # pallas flash
            group.append((model_name, batch, seq, False))  # blockwise XLA
        groups.append(group)
    return groups


def _free_device_memory():
    """Delete every live device array between ladder attempts: a failed
    attempt leaves its params resident (the exception frame pins them) and
    OOMs every config after it — the round-3 1.3B cascade. Also run between
    SUCCESSFUL configs so the next (bigger) model starts from empty HBM."""
    import gc
    import jax
    gc.collect()
    for a in jax.live_arrays():
        try:
            a.delete()
        except Exception:  # noqa: BLE001
            pass
    jax.clear_caches()
    gc.collect()


def run_groups(groups, runner, emit, log=lambda m: None, cleanup=None,
               deadline=None):
    """Walk the ladder smallest->largest. Within a group, first success
    wins; every success is emit()ed immediately (progressive evidence).
    Returns the best result seen (highest mfu, then value)."""
    best = None
    last_err = None
    for group in groups:
        if deadline is not None and time.time() > deadline:
            log("child deadline reached; stopping ladder")
            break
        for model_name, batch, seq, use_flash in group:
            attn = "pallas" if use_flash else "blockwise"
            try:
                result = runner(model_name, batch, seq, use_flash)
            except Exception as e:  # OOM or compile failure: next variant
                last_err = e
                log(f"bench config {model_name} bs={batch} attn={attn} "
                    f"failed: {str(e)[:200]}")
                if cleanup is not None:
                    try:
                        cleanup()
                    except Exception as ce:  # noqa: BLE001
                        log(f"inter-attempt cleanup failed: {ce}")
                continue
            emit(result)
            if _better(result, best):
                best = result
            if cleanup is not None:
                try:
                    cleanup()
                except Exception as ce:  # noqa: BLE001
                    log(f"inter-group cleanup failed: {ce}")
            break  # group satisfied; move to the next (bigger) config
    if best is not None:
        return best
    return {"metric": "GPT pretrain tokens/sec/chip", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": str(last_err)[:300] if last_err else "no config ran"}


def _better(a, b):
    if b is None:
        return True
    ka = (a.get("mfu", 0.0) or 0.0, a.get("value", 0.0) or 0.0)
    kb = (b.get("mfu", 0.0) or 0.0, b.get("value", 0.0) or 0.0)
    return ka > kb


def child_main(kind, results_path):
    """Runs in a subprocess. kind: 'tpu' (default backend — the plugin
    claims the chip) or 'cpu' (forced CPU platform)."""
    if kind == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if kind == "cpu":
        try:
            # jax.config.update is the only mechanism that reliably forces
            # cpu (the TPU plugin's .pth hook overrides env vars).
            jax.config.update("jax_platforms", "cpu")
        except Exception as e:  # noqa: BLE001
            _log(f"could not force cpu platform ({e})")
    # persistent XLA compilation cache: the driver's end-of-round bench run
    # hits warm artifacts instead of paying the 1.3B-scan compile again
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    t_claim = time.time()
    backend = jax.default_backend()  # may block while claiming the chip
    _log(f"child[{kind}]: backend={backend} "
         f"(claim took {time.time() - t_claim:.1f}s)")
    on_tpu = backend == "tpu"
    if kind == "tpu" and not on_tpu:
        # TPU init fell back to another platform (plugin failure). Exiting
        # without results lets the parent keep retrying the real chip — a
        # silent CPU number must never masquerade as the TPU result.
        _log("child[tpu]: backend is not tpu; exiting for relaunch")
        return 3

    def emit(result):
        with open(results_path, "a") as f:
            f.write(json.dumps(result) + "\n")
            f.flush()
            os.fsync(f.fileno())
        _log(f"child[{kind}]: emitted {result.get('metric', '?')} "
             f"value={result.get('value')} mfu={result.get('mfu')}")

    deadline = None
    budget = os.environ.get("BENCH_CHILD_BUDGET_S")
    if budget:
        deadline = time.time() + float(budget)
    best = run_groups(build_groups(on_tpu),
                      lambda m, b, s, f: run(m, b, s,
                                             steps=10 if on_tpu else 2,
                                             warmup=2 if on_tpu else 1,
                                             use_flash=f),
                      emit, log=_log, cleanup=_free_device_memory,
                      deadline=deadline)
    if best.get("value", 0.0) <= 0.0:
        # total failure: surface the root-cause error in the results file so
        # the final JSON carries it instead of a generic message
        best.setdefault("backend", backend)
        emit(best)
    return 0


# --------------------------------------------------------------------------
# parent side: pure python, signal-safe, never touches jax
# --------------------------------------------------------------------------

def _read_results(path):
    results = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    results.append(json.loads(line))
                except ValueError:
                    pass
    except OSError:
        pass
    return results


def _best_of(results):
    best = None
    for r in results:
        if _better(r, best):
            best = r
    return best


class _Parent:
    def __init__(self):
        self.results_path = tempfile.NamedTemporaryFile(
            prefix="bench_results_", suffix=".jsonl", delete=False).name
        self.printed = False
        self.child = None

    def emit_best(self, note=None):
        """Print the final JSON line exactly once."""
        if self.printed:
            return
        self.printed = True
        best = _best_of(_read_results(self.results_path))
        if best is None:
            best = {"metric": "GPT pretrain tokens/sec/chip", "value": 0.0,
                    "unit": "tokens/s/chip", "vs_baseline": 0.0,
                    "error": "no config completed within the bench window"}
        if note and "note" not in best:
            best["note"] = note
        print(json.dumps(best))
        sys.stdout.flush()

    def on_signal(self, signum, frame):
        _log(f"parent got signal {signum}; emitting best-so-far")
        self.emit_best(note="emitted from signal handler (driver timeout); "
                            "result is the best config completed so far")
        # Never kill a TPU-attached child (killing mid-claim wedges the
        # tunnel); orphan it — it exits on its own once the claim resolves.
        os._exit(0)

    def launch(self, kind):
        _log(f"launching {kind} child...")
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child", kind,
             self.results_path],
            stdout=sys.stderr, stderr=sys.stderr)


def main():
    parent = _Parent()
    signal.signal(signal.SIGTERM, parent.on_signal)
    signal.signal(signal.SIGINT, parent.on_signal)

    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "1680"))
    t_end = _T0 + total_budget
    # reserve a slice at the end for the CPU fallback if TPU yields nothing
    cpu_reserve = float(os.environ.get("BENCH_CPU_RESERVE_S", "300"))

    def _have_real_result():
        return any(r.get("value", 0.0) > 0.0
                   for r in _read_results(parent.results_path))

    child = parent.launch("tpu")
    fast_fails = 0
    launched = time.time()
    while True:
        now = time.time()
        # leave the reserve slice for cpu fallback only while we have nothing
        deadline = t_end - (0 if _have_real_result() else cpu_reserve)
        if now >= deadline:
            if child.poll() is None:
                _log("parent deadline; orphaning still-running TPU child")
            break
        try:
            rc = child.wait(timeout=min(15.0, max(1.0, deadline - now)))
        except subprocess.TimeoutExpired:
            continue
        # child exited; a value>0 line means real evidence was captured
        # (error-only lines keep the retry loop going)
        if _have_real_result():
            _log(f"TPU child exited rc={rc} with results captured")
            break
        if rc != 0 and time.time() - launched < 30.0:
            fast_fails += 1
            if fast_fails >= 3:
                _log("3 consecutive fast failures; giving up on TPU")
                break
        else:
            fast_fails = 0
        if time.time() >= deadline - 20.0:
            break
        _log(f"TPU child exited rc={rc} with no usable result; relaunching "
             f"({deadline - time.time():.0f}s left)...")
        time.sleep(10.0)
        child = parent.launch("tpu")
        launched = time.time()

    if not _have_real_result():
        # CPU fallback: honest metadata pointing at committed on-hardware
        # measurements from earlier in the round
        _log("no TPU result; running CPU fallback child...")
        remaining = max(30.0, t_end - time.time() + 60.0)
        cpu_child = parent.launch("cpu")
        try:
            cpu_child.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            _log("CPU fallback child still running at budget end")
        parent.emit_best(
            note="cpu fallback (TPU tunnel unavailable at capture time); "
                 "measured-on-TPU evidence for this round is committed in "
                 "TPU_SMOKE.log and BENCH_SELFRUN_r05.json (this same "
                 "ladder, run on-chip earlier in the round)")
    else:
        parent.emit_best()


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        sys.exit(child_main(sys.argv[2], sys.argv[3]))
    main()
