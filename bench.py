#!/usr/bin/env python
"""Headline benchmark: GPT-3-class pretraining throughput on one TPU chip.

Metric (BASELINE.json): tokens/sec/chip + MFU for GPT-3 1.3B-13B.
Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": "tokens/s/chip",
   "vs_baseline": mfu / 0.45, ...}
vs_baseline compares achieved MFU against the 45% north-star (BASELINE.json).

Runs the flagship hybrid train step (scan-over-layers, remat, pallas flash
attention, bf16 compute, fused AdamW, donated buffers). Falls back to smaller
configs on OOM; CPU gets a tiny config so the line always prints.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def peak_flops_bf16(device_kind: str) -> float:
    dk = device_kind.lower()
    table = {
        "v6": 918e12, "v5p": 459e12, "v5 lite": 197e12, "v5e": 197e12,
        "v4": 275e12, "v3": 123e12, "v2": 45e12,
    }
    for k, v in table.items():
        if k in dk:
            return v
    return 197e12  # conservative default


def model_flops_per_token(cfg, seq_len):
    """6N matmul + attention term (per training token, fwd+bwd)."""
    H, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    n_params = 12 * L * H * H + V * H * 2 + cfg.max_seq_len * H
    attn = 12 * L * H * seq_len  # 2*2*S*H per layer fwd, x3 with bwd
    return 6 * n_params + attn, n_params


_T0 = time.time()


def _log(msg):
    sys.stderr.write(f"[bench +{time.time() - _T0:7.1f}s] {msg}\n")
    sys.stderr.flush()


def run(model_name, batch, seq, steps=10, warmup=2, use_flash=True):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT_CONFIGS
    from paddle_tpu.models.gpt_hybrid import HybridTrainStep

    cfg = GPT_CONFIGS[model_name]
    cfg.max_seq_len = max(cfg.max_seq_len, seq)
    on_tpu = jax.default_backend() == "tpu"
    cfg.use_flash = use_flash and on_tpu
    cfg.compute_dtype = "bfloat16" if on_tpu else "float32"
    cfg.remat = True

    # bf16 params; moments drop to bf16 storage when fp32 moments alone would
    # crowd a 16G chip (>= ~1B params: 2 + 8 bytes/param > half of HBM). The
    # measured alternative is a guaranteed compile-time HBM OOM ("Used 20.4G
    # of 15.75G") — bf16 moments are the single-chip analog of the
    # reference's ZeRO moment sharding across a GPU pod.
    _, n_params = model_flops_per_token(cfg, seq)
    moment_dtype = "bfloat16" if (on_tpu and n_params > 1.0e9) else "float32"
    opt = paddle.optimizer.AdamW(2e-4, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
                                 moment_dtype=moment_dtype)
    param_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    _log(f"{model_name} bs={batch} seq={seq}: init params...")
    step = HybridTrainStep(cfg, opt, param_dtype=param_dtype)
    key = jax.random.key(0)
    ids = jax.random.randint(key, (batch, seq), 0, cfg.vocab_size, jnp.int32)

    _log("warmup (includes XLA compile)...")
    for _ in range(warmup):
        loss = step(ids)
    # device_get, NOT block_until_ready: the axon remote platform's
    # block_until_ready returns before remote execution finishes (measured:
    # "6000 TFLOP/s" on a 197-TFLOP chip). Fetching the scalar forces a
    # genuine round-trip sync and costs only the scalar transfer.
    jax.device_get(loss)
    _log("timed steps...")
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids)
    jax.device_get(loss)
    dt = (time.perf_counter() - t0) / steps
    tokens_per_sec = batch * seq / dt
    fpt, n_params = model_flops_per_token(cfg, seq)
    dev = jax.devices()[0]
    peak = peak_flops_bf16(getattr(dev, "device_kind", "unknown"))
    mfu = tokens_per_sec * fpt / peak
    attn = "pallas" if cfg.use_flash else "blockwise"
    # step-time breakdown: time the forward alone (shares param buffers),
    # the remainder is backward(+remat recompute)+optimizer
    breakdown = None
    if on_tpu and os.environ.get("BENCH_BREAKDOWN", "1") != "0":
        try:
            _log("breakdown: forward-only timing...")
            l = step.loss_only(ids)
            jax.device_get(l)
            t0 = time.perf_counter()
            for _ in range(max(steps // 2, 3)):
                l = step.loss_only(ids)
            jax.device_get(l)
            fwd_s = (time.perf_counter() - t0) / max(steps // 2, 3)
            breakdown = {"fwd_s": round(fwd_s, 4),
                         "bwd_opt_s": round(dt - fwd_s, 4)}
        except Exception as e:  # noqa: BLE001 — breakdown is best-effort
            _log(f"breakdown probe failed: {str(e)[:120]}")
    return {
        "metric": f"GPT pretrain tokens/sec/chip ({model_name}, seq={seq}, "
                  f"bs={batch}, bf16+remat+attn={attn}, 1 chip)",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.45, 4),
        "mfu": round(mfu, 4),
        "step_time_s": round(dt, 4),
        "loss": float(np.asarray(jax.device_get(loss))),
        "n_params": n_params,
        "attention": attn,
        "device": getattr(dev, "device_kind", str(dev)),
        "peak_flops_assumed": peak,
        **({"breakdown": breakdown} if breakdown else {}),
    }


def probe_backend():
    """Decide which backend to use WITHOUT wedging the whole bench.

    TPU plugin init can fail fast (UNAVAILABLE) or hang (a dead client's
    chip claim takes minutes to expire server-side). Round-3 lesson: ONE
    600s probe then permanent cpu fallback threw the round's hardware
    evidence away over a transient wedge. Now: a single claimant child at a
    time (two concurrent clients would contend for the chip), waited on in
    60s slices across a long window (BENCH_PROBE_TIMEOUT_S, default 1800s —
    the var keeps its old meaning of total probe budget). A hung child is
    simply waited on — the claim resolves server-side and the child then
    finishes on its own; a child that exits with an error is relaunched
    after a short backoff. cpu fallback only when the window is exhausted.
    """
    import subprocess
    import tempfile
    window = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "1800"))
    code = ("import jax; d = jax.devices()[0]; "
            "print('BACKEND=' + jax.default_backend())")
    t0 = time.time()
    child = None
    out_f = None
    attempt = 0
    fast_fails = 0
    try:
        while time.time() - t0 < window:
            if child is None:
                attempt += 1
                _log(f"backend probe attempt {attempt} "
                     f"({window - (time.time() - t0):.0f}s left)...")
                out_f = tempfile.NamedTemporaryFile(
                    "w+", prefix="bench_probe_", delete=False)
                launched = time.time()
                child = subprocess.Popen([sys.executable, "-c", code],
                                         stdout=out_f,
                                         stderr=subprocess.STDOUT)
            try:
                rc = child.wait(timeout=min(
                    60.0, max(1.0, window - (time.time() - t0))))
            except subprocess.TimeoutExpired:
                continue  # still claiming; keep waiting on the SAME child
            out_f.seek(0)
            backend = None
            tail = []
            for line in out_f:
                tail.append(line.rstrip())
                if line.startswith("BACKEND="):
                    backend = line.split("=", 1)[1].strip()
            out_f.close()
            os.unlink(out_f.name)
            out_f = None
            if backend is not None:
                _log(f"backend probe succeeded: {backend}")
                return backend
            _log(f"probe child exited rc={rc} without a backend; "
                 f"output tail: {' | '.join(tail[-3:])[:400]}")
            # A fast non-zero exit is deterministic breakage, not a wedge —
            # don't burn the whole window relaunching it.
            if time.time() - launched < 30.0:
                fast_fails += 1
                if fast_fails >= 3:
                    _log("3 consecutive fast failures; falling back to cpu")
                    return None
            else:
                fast_fails = 0
            child = None
            time.sleep(min(15.0, max(0.0, window - (time.time() - t0))))
    except Exception as e:  # noqa: BLE001  (the JSON line must always print)
        _log(f"backend probe failed: {e}")
        return None
    finally:
        # Never kill a TPU-attached child (killing mid-claim wedges the
        # tunnel); if one is still claiming at window end, orphan it — it
        # exits on its own once the claim resolves (it holds its own
        # inherited fd, so the parent's handle closes unconditionally).
        if out_f is not None:
            out_f.close()
            if child is None or child.poll() is not None:
                try:
                    os.unlink(out_f.name)
                except OSError:
                    pass
            else:
                _log("orphaning still-blocked probe child (exits on its own)")
    _log(f"backend probe window ({window:.0f}s) exhausted after "
         f"{attempt} attempts; falling back to cpu")
    return None


def main():
    backend = probe_backend()
    if backend is None:
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if backend is None:
        # jax.config.update is the only mechanism that reliably forces cpu
        # here (the plugin's .pth hook overrides env vars). If it fails we
        # must not risk initializing the wedged TPU backend — emit the
        # fallback line and stop.
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception as e:  # noqa: BLE001
            _log(f"could not force cpu platform ({e}); aborting")
            print(json.dumps({"metric": "GPT pretrain tokens/sec/chip",
                              "value": 0.0, "unit": "tokens/s/chip",
                              "vs_baseline": 0.0,
                              "error": f"cpu fallback unavailable: {e}"}))
            return
    # persistent XLA compilation cache: the driver's end-of-round bench run
    # hits warm artifacts instead of paying the 1.3B-scan compile again
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception as e:  # noqa: BLE001
        _log(f"default_backend() raised ({e}); assuming cpu")
        on_tpu = False
    result = run_ladder(build_attempts(on_tpu),
                        lambda m, b, s, f: run(
                            m, b, s, steps=10 if on_tpu else 2,
                            warmup=2 if on_tpu else 1, use_flash=f),
                        log=_log, cleanup=_free_device_memory)
    if not on_tpu:
        # honest metadata for the fallback case: point at the committed
        # on-hardware measurements from earlier in the round
        result["note"] = ("cpu fallback (TPU tunnel unavailable at capture "
                          "time); measured-on-TPU evidence for this round "
                          "is committed in TPU_SMOKE.log "
                          "(gpt3-1.3B bs8 seq2048: 9838 tok/s, 48.5% MFU)")
    print(json.dumps(result))


def build_attempts(on_tpu):
    """Fallback ladder: per config, pallas flash first, then the blockwise
    XLA attention (a kernel regression must never zero the round's perf
    evidence again — round-2 lesson), then smaller batch / smaller model."""
    if not on_tpu:
        # cpu fallback keeps the JSON line printing; the round's real-TPU
        # measurements (when the tunnel was up) live in TPU_SMOKE.log
        return [("gpt3-125M", 2, 256, False)]
    ladder = []
    for model_name, batch, seq in [("gpt3-1.3B", 8, 2048),
                                   ("gpt3-1.3B", 4, 2048),
                                   ("gpt3-760M", 8, 2048),
                                   ("gpt3-345M", 8, 2048)]:
        ladder.append((model_name, batch, seq, True))   # pallas flash
        ladder.append((model_name, batch, seq, False))  # blockwise XLA
    return ladder


def _free_device_memory():
    """Delete every live device array between ladder attempts: a failed
    attempt leaves its params resident (the exception frame pins them) and
    OOMs every config after it — the round-3 1.3B cascade."""
    import gc
    import jax
    gc.collect()
    for a in jax.live_arrays():
        try:
            a.delete()
        except Exception:  # noqa: BLE001
            pass
    jax.clear_caches()
    gc.collect()


def run_ladder(attempts, runner, log=lambda m: None, cleanup=None):
    """Try each (model, batch, seq, use_flash) until one produces a result;
    the returned dict records which attention path actually ran."""
    last_err = None
    for model_name, batch, seq, use_flash in attempts:
        attn = "pallas" if use_flash else "blockwise"
        try:
            return runner(model_name, batch, seq, use_flash)
        except Exception as e:  # OOM or compile failure: walk down the ladder
            last_err = e
            log(f"bench config {model_name} bs={batch} attn={attn} failed: "
                f"{str(e)[:200]}")
            if cleanup is not None:
                try:
                    cleanup()
                except Exception as ce:  # noqa: BLE001
                    log(f"inter-attempt cleanup failed: {ce}")
            continue
    return {"metric": "GPT pretrain tokens/sec/chip", "value": 0.0,
            "unit": "tokens/s/chip", "vs_baseline": 0.0,
            "error": str(last_err)[:300]}


if __name__ == "__main__":
    main()
