#!/usr/bin/env python
"""SLO chaos ladder: multi-tenant traffic management smoke on CPU
(JAX_PLATFORMS=cpu), exercising priority classes, load shedding,
autoscaling and hot weight swaps end to end under deterministic chaos.

Rungs (each seeded; traffic comes from fault_injection.ArrivalSurge, so
two runs see IDENTICAL arrivals step for step):

  1. surge-shed-recover — mixed interactive/batch/best_effort traffic
       through a sustained arrival surge with shedding + priority
       admission on: EVERY interactive request completes (zero dropped,
       none shed), best_effort degrades VISIBLY (shed > 0, retry-after
       hints attached, shed queue-wait in the ledger) and RECOVERABLY
       (post-surge best_effort completes again).
  2. upgrade-under-load — rolling_restart(new_params=) mid-traffic on a
       2-replica fleet: zero requests dropped, every result is
       SINGLE-VERSION consistent (tokens bitwise equal the golden
       reference for the weight version stamped on the result), the
       fleet converges to the new version, zero retraces.
  3. kill-during-surge — one replica killed (FaultPlan, abrupt) while
       the surge is at peak, snapshot respawn + replay: zero interactive
       requests dropped, interactive results bitwise.

Quick mode (default; tier-1 runs it via tests/test_slo_serving.py) keeps
every gate STRUCTURAL — counts, versions, bitwise tokens — so it cannot
flake under CI load. Full mode (--full) additionally gates the
interactive-class p99 TTFT under chaos against a calm-baseline multiple
(the ROADMAP "p99 held through surge + upgrade + kill" gate) and prints
the latency table.

  python tools_slo_smoke.py [--full] [--seed S]

Prints, machine-greppable:

  SLO_SMOKE <rung>: <status>  <details>
"""
from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

_FIXTURE = None

# every SLO knob the ladder touches, pinned to a known state per rung
BASE_FLAGS = {
    "FLAGS_serving_priority_classes": False,
    "FLAGS_serving_shed": False,
    "FLAGS_serving_shed_high": 0.75,
    "FLAGS_serving_shed_low": 0.5,
    "FLAGS_serving_shed_window": 4,
    "FLAGS_serving_preempt_margin_s": 0.0,
    "FLAGS_serving_tenant_rate": 0.0,
    "FLAGS_serving_autoscale": False,
}


def _fixture():
    """Tiny GPT + helpers, built once (executables are memoized per
    config, so every rung reuses the same compiled fused step). Two
    weight versions: v0 serves, v1 is the hot-upgrade target."""
    global _FIXTURE
    if _FIXTURE is not None:
        return _FIXTURE
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate_from_params
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import init_gpt_params

    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=128, dropout=0.0, use_flash=False,
                    compute_dtype="float32", remat=False)
    p0 = init_gpt_params(cfg, jax.random.key(0))
    p1 = init_gpt_params(cfg, jax.random.key(1))

    def factory(**kw):
        kw.setdefault("num_slots", 3)
        kw.setdefault("max_seq_len", 96)
        kw.setdefault("page_size", 8)
        kw.setdefault("prefill_chunk", 8)
        kw.setdefault("kv_layout", "paged")
        return serving.Engine(params=p0, config=cfg, **kw)

    _ref_cache = {}

    def ref(params_id, prompt, n, **kw):
        key = (params_id, tuple(np.asarray(prompt).tolist()), n,
               tuple(sorted(kw.items())))
        if key not in _ref_cache:
            params = p0 if params_id == 0 else p1
            out = np.asarray(generate_from_params(
                params, np.asarray(prompt)[None], cfg, max_new_tokens=n,
                **kw)._data)
            _ref_cache[key] = out[0, len(prompt):].tolist()
        return _ref_cache[key]

    _FIXTURE = (paddle, serving, cfg, p0, p1, factory, ref)
    return _FIXTURE


class _Traffic:
    """Deterministic mixed-class request stream: tenants 'web'
    (interactive, generous deadline), 'analytics' (batch) and 'scavenger'
    (best_effort), greedy and sampled interleaved."""

    def __init__(self, serving, seed, interactive_deadline=30.0):
        self.serving = serving
        self.rng = np.random.default_rng(seed)
        self.n = 0
        self.deadline = interactive_deadline

    def next(self):
        i = self.n
        self.n += 1
        cls, tenant, dl = [
            ("interactive", "web", self.deadline),
            ("batch", "analytics", None),
            ("best_effort", "scavenger", None),
            ("best_effort", "scavenger", None),
        ][i % 4]
        kw = {}
        if i % 3 == 2:
            kw = {"do_sample": True, "temperature": 0.7 + 0.05 * (i % 5),
                  "top_p": 0.9, "seed": 100 + i}
        return self.serving.Request(
            self.rng.integers(0, 97, 4 + (i % 4) * 2),
            max_new_tokens=3 + (i % 3), priority=cls, tenant=tenant,
            deadline_s=dl, **kw)


def _golden_kw(r):
    return ({"do_sample": True, "temperature": r.temperature,
             "top_p": r.top_p, "seed": r.seed} if r.do_sample else {})


def _drive(sup, traffic, total_steps, on_step=None):
    """The surge driver: at every boundary poll the deterministic surge
    schedule, submit that many requests, run one supervision round.
    Returns (submitted, refused) — refused carries (request, error) for
    ShedError / QueueFullError refusals (the visible degradation)."""
    from paddle_tpu.serving import QueueFullError
    from paddle_tpu.utils import fault_injection as fi

    submitted, refused = [], []
    step = 0
    while step < total_steps or sup.pending():
        for _ in range(fi.surge_arrivals(step)):
            req = traffic.next()
            try:
                sup.submit(req)
                submitted.append(req)
            except QueueFullError as e:   # ShedError subclasses it
                refused.append((req, e))
        if on_step is not None:
            on_step(step)
        sup.step()
        step += 1
        if step > 100000:
            raise RuntimeError("ladder did not converge")
    return submitted, refused


def rung_surge_shed_recover(seed=7):
    """Sustained surge with shedding + priority admission: interactive
    holds, best_effort sheds visibly and recovers."""
    paddle, serving, cfg, p0, p1, factory, ref = _fixture()
    from paddle_tpu.serving import ServingSupervisor
    from paddle_tpu.serving import metrics as sm
    from paddle_tpu.utils import fault_injection as fi

    paddle.set_flags(dict(BASE_FLAGS))
    sm.reset_serving_counters()
    sup = ServingSupervisor(
        lambda: factory(priority=True, shed=True, max_queue=12),
        num_replicas=1)
    traffic = _Traffic(serving, seed)
    surge = fi.ArrivalSurge(base_rate=0.4, surge_rate=5.0, surge_start=4,
                            surge_steps=24, total_steps=120, seed=seed)
    paddle.set_flags({"FLAGS_serving_shed_window": 3})
    with fi.inject(fi.FaultPlan(surge=surge)):
        submitted, refused = _drive(sup, traffic, surge.total_steps)
    results = sup.pop_results()

    # recovery: the surge is over and the queue drained — fresh
    # best_effort traffic must be served again (the shed latch released)
    recov = [traffic.next() for _ in range(2)]
    for r in recov:
        r.priority, r.tenant = "best_effort", "scavenger"
    recov_results = sup.run(recov)
    paddle.set_flags(dict(BASE_FLAGS))
    recovered = all(
        recov_results.get(r.request_id) is not None
        and recov_results[r.request_id].finish_reason in ("stop", "length")
        for r in recov)

    inter = [r for r in submitted if r.priority == "interactive"]
    inter_done = [r for r in inter
                  if results.get(r.request_id) is not None
                  and results[r.request_id].finish_reason
                  in ("stop", "length")]
    shed_results = [r for r in results.values() if r.finish_reason == "shed"]
    refused_shed = [e for _, e in refused
                    if getattr(e, "retry_after", None) is not None]
    c = sm.serving_counters()
    ok = (len(inter_done) == len(inter) and len(inter) > 0
          and c["shed"] > 0
          and all(r.retry_after is not None and r.retry_after > 0
                  for r in shed_results)
          and all(r.priority != "interactive" for r in shed_results)
          and c["dropped"] == 0
          and all(e.retry_after > 0 for e in refused_shed)
          and recovered)
    return {"ok": ok, "interactive": f"{len(inter_done)}/{len(inter)}",
            "shed": c["shed"], "refused": len(refused),
            "shed_wait_ms": round(c["shed_queue_wait_mean"] * 1e3, 1),
            "recovered": recovered,
            "summary_visible": "slo:" in sm.serving_summary()}


def rung_upgrade_under_load(seed=11):
    """Hot weight swap mid-traffic: zero drops, single-version bitwise
    results, fleet converges to the new version, zero retraces."""
    paddle, serving, cfg, p0, p1, factory, ref = _fixture()
    from paddle_tpu.serving import ServingSupervisor
    from paddle_tpu.serving import metrics as sm
    from paddle_tpu.utils import fault_injection as fi

    paddle.set_flags(dict(BASE_FLAGS))
    sm.reset_serving_counters()
    sup = ServingSupervisor(lambda: factory(max_queue=64), num_replicas=2)
    traffic = _Traffic(serving, seed)
    surge = fi.ArrivalSurge(base_rate=1.0, surge_rate=1.0, surge_start=0,
                            surge_steps=40, total_steps=40, seed=seed)
    swapped = []

    def on_step(step):
        if step == 12:
            t0 = sm.serving_counters()["paged_traces"]
            sup.rolling_restart(absorb_steps=1, new_params=p1)
            swapped.append(sm.serving_counters()["paged_traces"] - t0)

    with fi.inject(fi.FaultPlan(surge=surge)):
        submitted, refused = _drive(sup, traffic, surge.total_steps,
                                    on_step=on_step)
    results = sup.pop_results()

    done = [r for r in submitted if results.get(r.request_id) is not None]
    missing = len(submitted) - len(done)
    wrong = []
    for r in done:
        res = results[r.request_id]
        if res.finish_reason not in ("stop", "length"):
            continue
        gold = ref(res.params_version, r.prompt, r.max_new_tokens,
                   **_golden_kw(r))
        if res.tokens != gold:
            wrong.append(r.request_id)
    versions = sorted({res.params_version for res in results.values()
                       if res.params_version is not None})
    tel = sup.telemetry()
    post_versions = {tel[f"replica{i.idx}"]["params_version"]
                     for i in sup._replicas if i.engine is not None}
    c = sm.serving_counters()
    ok = (missing == 0 and not wrong and c["dropped"] == 0
          and swapped == [0]                 # the swap added ZERO retraces
          and post_versions == {1}
          and c["weight_swaps"] >= 2 and c["rolling_restarts"] == 1)
    return {"ok": ok, "requests": len(submitted), "missing": missing,
            "wrong": wrong, "versions_served": versions,
            "fleet_version": sorted(post_versions),
            "swap_retraces": swapped, "weight_swaps": c["weight_swaps"]}


def rung_kill_during_surge(seed=13):
    """Abrupt replica kill at surge peak: snapshot respawn + replay keep
    zero interactive drops and interactive results bitwise."""
    paddle, serving, cfg, p0, p1, factory, ref = _fixture()
    from paddle_tpu.serving import ServingSupervisor
    from paddle_tpu.serving import metrics as sm
    from paddle_tpu.utils import fault_injection as fi

    paddle.set_flags(dict(BASE_FLAGS))
    paddle.set_flags({"FLAGS_serving_preempt_margin_s": 60.0})
    sm.reset_serving_counters()
    d = tempfile.mkdtemp(prefix="slo_chaos_")
    try:
        sup = ServingSupervisor(
            lambda: factory(priority=True, max_queue=64),
            num_replicas=2, snapshot_dir=d, snapshot_every=2)
        traffic = _Traffic(serving, seed)
        surge = fi.ArrivalSurge(base_rate=0.5, surge_rate=4.0,
                                surge_start=4, surge_steps=16,
                                total_steps=80, seed=seed)
        plan = fi.FaultPlan(surge=surge, kill_at_decode_step=8,
                            kill_engine_tag="replica1")
        with fi.inject(plan):
            submitted, refused = _drive(sup, traffic, surge.total_steps)
        results = sup.pop_results()
        c = sm.serving_counters()
        inter = [r for r in submitted if r.priority == "interactive"]
        inter_wrong, inter_missing = [], []
        for r in inter:
            res = results.get(r.request_id)
            if res is None or res.finish_reason not in ("stop", "length"):
                inter_missing.append(r.request_id)
                continue
            gold = ref(res.params_version, r.prompt, r.max_new_tokens,
                       **_golden_kw(r))
            if res.tokens != gold:
                inter_wrong.append(r.request_id)
        ok = (plan.stats["serving_kills"] == 1
              and not inter_missing and not inter_wrong and len(inter) > 0
              and c["dropped"] == 0 and c["respawns"] >= 1)
        return {"ok": ok, "interactive": len(inter),
                "missing": inter_missing, "wrong": inter_wrong,
                "respawns": c["respawns"], "replayed": c["replayed"],
                "preempted": c["preempted"],
                "kills": plan.stats["serving_kills"]}
    finally:
        paddle.set_flags(dict(BASE_FLAGS))
        shutil.rmtree(d, ignore_errors=True)


def _interactive_p99(results, submitted):
    ttfts = [results[r.request_id].ttft for r in submitted
             if r.priority == "interactive"
             and results.get(r.request_id) is not None
             and results[r.request_id].ttft is not None]
    return float(np.percentile(ttfts, 99)) if ttfts else None


def rung_p99_held(seed=17):
    """Full-mode gate: interactive p99 TTFT through surge + upgrade +
    kill stays within a generous multiple of the calm baseline (absolute
    CPU numbers vary with CI load; the RATIO is the story)."""
    paddle, serving, cfg, p0, p1, factory, ref = _fixture()
    from paddle_tpu.serving import ServingSupervisor
    from paddle_tpu.serving import metrics as sm
    from paddle_tpu.utils import fault_injection as fi

    paddle.set_flags(dict(BASE_FLAGS))
    paddle.set_flags({"FLAGS_serving_preempt_margin_s": 60.0})

    def run(chaos):
        sm.reset_serving_counters()
        d = tempfile.mkdtemp(prefix="slo_p99_")
        try:
            sup = ServingSupervisor(
                lambda: factory(priority=True, shed=True, max_queue=14),
                num_replicas=2, snapshot_dir=d, snapshot_every=2)
            traffic = _Traffic(serving, seed)
            surge = fi.ArrivalSurge(
                base_rate=0.5, surge_rate=4.0 if chaos else 0.5,
                surge_start=6, surge_steps=20, total_steps=140, seed=seed)
            plan = fi.FaultPlan(
                surge=surge,
                kill_at_decode_step=10 if chaos else None,
                kill_engine_tag="replica1" if chaos else None)

            def on_step(step):
                if chaos and step == 9:
                    sup.rolling_restart(absorb_steps=1, new_params=p1)

            with fi.inject(plan):
                submitted, _ = _drive(sup, traffic, surge.total_steps,
                                      on_step=on_step)
            results = sup.pop_results()
            inter = [r for r in submitted if r.priority == "interactive"]
            missing = [r.request_id for r in inter
                       if results.get(r.request_id) is None
                       or results[r.request_id].finish_reason
                       not in ("stop", "length")]
            return _interactive_p99(results, submitted), missing, \
                sm.serving_counters()
        finally:
            shutil.rmtree(d, ignore_errors=True)

    calm_p99, calm_missing, _ = run(chaos=False)
    chaos_p99, chaos_missing, c = run(chaos=True)
    paddle.set_flags(dict(BASE_FLAGS))
    ok = (not calm_missing and not chaos_missing
          and calm_p99 is not None and chaos_p99 is not None
          and chaos_p99 <= max(10.0 * calm_p99, 2.0)
          and c["dropped"] == 0 and c["shed"] > 0)
    return {"ok": ok, "calm_p99_ms": round(calm_p99 * 1e3, 1),
            "chaos_p99_ms": round(chaos_p99 * 1e3, 1),
            "interactive_missing": chaos_missing,
            "shed": c["shed"], "respawns": c["respawns"]}


def run_ladder(full=False, seed=7):
    out = {}
    out["surge_shed_recover"] = rung_surge_shed_recover(seed)
    out["upgrade_under_load"] = rung_upgrade_under_load(seed + 4)
    out["kill_during_surge"] = rung_kill_during_surge(seed + 6)
    if full:
        out["p99_held"] = rung_p99_held(seed + 10)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="also run the (timing-sensitive) p99 gate rung")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    out = run_ladder(full=args.full, seed=args.seed)
    failed = 0
    for rung, info in out.items():
        status = "OK" if info.pop("ok") else "FAIL"
        failed += status == "FAIL"
        detail = "  ".join(f"{k}={v}" for k, v in info.items())
        print(f"SLO_SMOKE {rung}: {status}  {detail}")
    from paddle_tpu.serving import metrics as sm
    print("SLO_SMOKE summary:", sm.serving_summary())
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
