#!/usr/bin/env python
"""Observability smoke: run a short train loop and a serving burst with the
unified telemetry ON, then gate the artifacts:

  * the exported per-request Perfetto/Chrome-trace JSON loads and its
    span timeline reconciles with the recorded TTFT/latency;
  * the JSONL trace sink emits one parseable line per finished request;
  * the Prometheus /metrics page parses line-by-line and carries every
    counter family;
  * steady-state trace-counter gates stay green with telemetry on
    (paged_traces frozen after warmup — tracing adds no executables);
  * telemetry-on vs telemetry-off train step time differs by <3%
    (the zero-overhead contract; full rung only — wall-clock gates are
    slow-marked, tier-1 runs the deterministic structural rungs).

  python tools_obs_smoke.py          # full ladder (incl. overhead gate)
  python tools_obs_smoke.py --fast   # structural rungs only (tier-1)
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

OVERHEAD_GATE_PCT = 3.0


def _tiny_cfg():
    from paddle_tpu.models.gpt import GPTConfig
    return GPTConfig(vocab_size=97, hidden_size=64, num_layers=2,
                     num_heads=4, max_seq_len=128, dropout=0.0,
                     use_flash=False, compute_dtype="float32", remat=False)


def _flags(**kw):
    import paddle_tpu as paddle
    paddle.set_flags(kw)


def train_rung(steps=8, verbose=True):
    """Short HybridTrainStep loop with step telemetry on: sampled records
    exist, carry the dispatch/sync split, and report MFU from the shared
    FLOP estimator."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models.gpt_hybrid import HybridTrainStep

    _flags(FLAGS_step_telemetry=True, FLAGS_step_telemetry_every=1)
    obs.reset_step_telemetry()
    try:
        cfg = _tiny_cfg()
        opt = paddle.optimizer.AdamW(1e-3)
        step = HybridTrainStep(cfg, opt)
        ids = jax.random.randint(jax.random.key(0), (2, 32), 0,
                                 cfg.vocab_size, jnp.int32)
        for _ in range(steps):
            step(ids)
        c = obs.step_counters()
        assert c["sampled"] == steps, c
        assert c["last_dispatch_s"] is not None
        assert c["last_sync_s"] is not None
        assert c["last_mfu"] is not None and c["last_mfu"] > 0
        assert c["flops_per_step"] > 0
        if verbose:
            print(f"TRAIN rung: {obs.step_summary()}", flush=True)
        return c
    finally:
        _flags(FLAGS_step_telemetry=False, FLAGS_step_telemetry_every=8)


def serving_rung(verbose=True):
    """Serving burst with span tracing on: every finished request's trace
    reconciles (queue.t0==submit, first_token==TTFT stamp,
    deliver==finish), the Perfetto export loads, the JSONL sink parses,
    and the paged trace counters freeze after warmup."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import serving, observability as obs
    from paddle_tpu.observability import tracing
    from paddle_tpu.models.gpt_hybrid import init_gpt_params
    from paddle_tpu.serving import metrics

    _flags(FLAGS_serving_trace=True)
    tracing.clear()
    jsonl_path = tempfile.mktemp(suffix=".jsonl", prefix="obs_trace_")
    sink = obs.JsonlTraceSink(jsonl_path)
    try:
        cfg = _tiny_cfg()
        params = init_gpt_params(cfg, jax.random.key(0))
        eng = serving.Engine(params=params, config=cfg, num_slots=3,
                             max_seq_len=96, kv_layout="paged",
                             page_size=8, prefill_chunk=16)
        rng = np.random.default_rng(0)
        reqs = [serving.Request(rng.integers(0, cfg.vocab_size, 12),
                                max_new_tokens=4) for _ in range(6)]
        results = eng.run(reqs)
        assert len(results) == len(reqs)
        base = metrics.serving_counters()["paged_traces"]
        # steady-state gate: more traffic over warm shapes must not trace
        more = [serving.Request(rng.integers(0, cfg.vocab_size, 12),
                                max_new_tokens=4) for _ in range(4)]
        eng.run(more)
        assert metrics.serving_counters()["paged_traces"] == base, \
            "tracing added executables"

        recs = tracing.traces()
        assert len(recs) >= len(reqs) + len(more)
        for rec in recs:
            spans = {s["name"]: s for s in rec["spans"]}
            q, ft, d = spans["queue"], spans["first_token"], spans["deliver"]
            assert abs((ft["t0"] - q["t0"]) - rec["ttft"]) < 1e-9
            assert abs((d["t0"] - q["t0"]) - rec["latency"]) < 1e-9

        trace_path = tempfile.mktemp(suffix=".json", prefix="obs_perfetto_")
        eng.export_trace(trace_path)
        data = json.load(open(trace_path))           # "Perfetto JSON loads"
        assert data["traceEvents"], "empty trace export"
        assert all("ph" in ev and "pid" in ev for ev in data["traceEvents"])
        os.unlink(trace_path)

        sink.close()
        lines = [json.loads(ln) for ln in open(jsonl_path)]
        assert len(lines) == len(recs)
        assert all("spans" in ln and "request_id" in ln for ln in lines)
        if verbose:
            print(f"SERVING rung: {len(recs)} traces, "
                  f"{sum(len(r['spans']) for r in recs)} spans, "
                  f"paged_traces frozen at {base}", flush=True)
        return recs
    finally:
        _flags(FLAGS_serving_trace=False)
        try:
            sink.close()
        except Exception:  # noqa: BLE001 — already closed on success
            pass
        if os.path.exists(jsonl_path):
            os.unlink(jsonl_path)


def prometheus_rung(verbose=True):
    """Start the /metrics endpoint on an ephemeral port, scrape it, parse
    the exposition page, and check every counter family is present."""
    from urllib.request import urlopen
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import prometheus

    srv = obs.start_metrics_server(port=0)
    try:
        text = urlopen(srv.url, timeout=10).read().decode()
        parsed = prometheus.parse(text)              # "the page parses"
        assert parsed, "empty exposition page"
        for fam in ("dispatch", "serving", "comm", "mp_comm", "fault",
                    "recovery", "step"):
            assert any(k.startswith(f"paddle_tpu_{fam}_") for k in parsed), \
                f"family {fam} missing from /metrics"
        if verbose:
            print(f"PROMETHEUS rung: {len(parsed)} series at {srv.url}",
                  flush=True)
        return parsed
    finally:
        obs.stop_metrics_server()


def overhead_rung(steps=40, trials=4, verbose=True):
    """Telemetry-on vs telemetry-off steady-state train step time, best of
    ``trials`` with the on/off measurements INTERLEAVED (machine-load
    drift between two back-to-back blocks would otherwise dwarf the <3%
    gate; wall-clock: full rung only)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import observability as obs
    from paddle_tpu.models.gpt_hybrid import HybridTrainStep

    cfg = _tiny_cfg()
    ids = jax.random.randint(jax.random.key(0), (2, 32), 0,
                             cfg.vocab_size, jnp.int32)

    def make_step():
        paddle.seed(0)
        step = HybridTrainStep(cfg, paddle.optimizer.AdamW(1e-3))
        for _ in range(5):                       # warm the executable
            step(ids)
        jax.block_until_ready(step.params["wte"])
        return step

    def one_trial(step, telemetry):
        _flags(FLAGS_step_telemetry=telemetry, FLAGS_step_telemetry_every=8)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(ids)
        jax.block_until_ready(loss)
        return (time.perf_counter() - t0) / steps

    try:
        obs.reset_step_telemetry()
        step = make_step()
        off = on = float("inf")
        for _ in range(trials):                  # interleave off/on pairs
            off = min(off, one_trial(step, False))
            on = min(on, one_trial(step, True))
        diff = (on - off) / off * 100.0
        if verbose:
            print(f"OVERHEAD rung: off {off * 1e3:.3f}ms  on "
                  f"{on * 1e3:.3f}ms  diff {diff:+.2f}% "
                  f"(gate <{OVERHEAD_GATE_PCT}%)", flush=True)
        assert diff < OVERHEAD_GATE_PCT, \
            f"telemetry overhead {diff:.2f}% exceeds {OVERHEAD_GATE_PCT}%"
        return off, on
    finally:
        _flags(FLAGS_step_telemetry=False, FLAGS_step_telemetry_every=8)


def main():
    fast = "--fast" in sys.argv
    train_rung()
    serving_rung()
    prometheus_rung()
    if not fast:
        overhead_rung()
    print("OBS SMOKE OK" + (" (fast)" if fast else ""), flush=True)


if __name__ == "__main__":
    main()
