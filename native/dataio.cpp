// paddle_tpu native data pipeline core.
//
// Host-side tokenized-corpus sampler mirroring the reference's C++ DataLoader
// workers / fleet data_generator (ref: paddle/fluid/operators/reader/*,
// python/paddle/distributed/fleet/data_generator) — redesigned for the TPU
// training loop:
//
//   * corpus = flat binary file of tokens (u16/u32/i64), mmap'd read-only
//   * sample order = stateless pseudo-random permutation (Feistel network with
//     cycle-walking) over non-overlapping seq_len windows -> no O(N) shuffle
//     buffer, O(1) checkpoint state (a single sample counter), seekable,
//     infinite multi-epoch stream (epoch e reshuffles by keying on e)
//   * worker threads claim batch indices and assemble [batch, seq_len+1]
//     int32 buffers in parallel; consumer emits batches strictly in order so
//     the stream is deterministic regardless of thread count
//
// Exposed as a plain C ABI consumed via ctypes (paddle_tpu/io/native.py).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// splitmix64 — the round-function mixer. Must match the Python fallback in
// paddle_tpu/io/native.py bit-for-bit.
// ---------------------------------------------------------------------------
static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// 4-round balanced Feistel permutation over [0, n) with cycle-walking.
// Stateless: perm(i) depends only on (i, n, key).
static inline uint64_t feistel_permute(uint64_t idx, uint64_t n, uint64_t key) {
  if (n <= 1) return 0;
  int bits = 0;
  while ((1ULL << bits) < n) bits++;
  int half = (bits + 1) / 2;
  uint64_t mask = (1ULL << half) - 1;
  uint64_t x = idx;
  do {
    uint64_t l = x >> half, r = x & mask;
    for (int round = 0; round < 4; round++) {
      uint64_t f = splitmix64(r ^ splitmix64(key + (uint64_t)round)) & mask;
      uint64_t nl = r, nr = l ^ f;
      l = nl;
      r = nr;
    }
    x = (l << half) | r;
  } while (x >= n);
  return x;
}

struct Corpus {
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t filesize = 0;
  int64_t ntokens = 0;
  int token_bytes = 2;
};

static inline int32_t read_token(const Corpus* c, int64_t i) {
  switch (c->token_bytes) {
    case 2:
      return (int32_t) * (const uint16_t*)(c->data + 2 * i);
    case 4:
      return (int32_t) * (const uint32_t*)(c->data + 4 * i);
    case 8:
      return (int32_t) * (const int64_t*)(c->data + 8 * i);
    default:
      return 0;
  }
}

struct Slot {
  std::vector<int32_t> buf;
  int64_t batch_idx = -1;
  uint64_t gen = 0;
};

struct Stream {
  Corpus* corpus = nullptr;
  int64_t seq_len = 0, batch = 0, nwindows = 0;
  uint64_t seed = 0;

  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  int64_t claim = 0;      // next absolute batch index a worker will take
  int64_t next_emit = 0;  // next absolute batch index the consumer emits
  uint64_t generation = 0;
  bool stop = false;

  std::vector<Slot> slots;
  std::deque<int> free_slots;
  std::vector<std::pair<int64_t, int>> ready;  // (batch_idx, slot_id)
  std::vector<std::thread> workers;
};

// Map absolute sample index -> window index in the corpus.
static inline int64_t sample_to_window(const Stream* s, int64_t sample) {
  uint64_t epoch = (uint64_t)(sample / s->nwindows);
  uint64_t in_epoch = (uint64_t)(sample % s->nwindows);
  uint64_t key = splitmix64(s->seed ^ splitmix64(epoch));
  return (int64_t)feistel_permute(in_epoch, (uint64_t)s->nwindows, key);
}

static void fill_batch(Stream* s, int64_t batch_idx, int32_t* out) {
  const int64_t row = s->seq_len + 1;
  for (int64_t j = 0; j < s->batch; j++) {
    int64_t w = sample_to_window(s, batch_idx * s->batch + j);
    int64_t base = w * s->seq_len;
    int32_t* dst = out + j * row;
    if (s->corpus->token_bytes == 4) {
      memcpy(dst, s->corpus->data + 4 * base, (size_t)row * 4);
    } else {
      for (int64_t t = 0; t < row; t++) dst[t] = read_token(s->corpus, base + t);
    }
  }
}

static void worker_main(Stream* s) {
  for (;;) {
    int slot_id;
    int64_t b;
    uint64_t gen;
    {
      std::unique_lock<std::mutex> lk(s->mu);
      s->cv_free.wait(lk, [&] { return s->stop || !s->free_slots.empty(); });
      if (s->stop) return;
      slot_id = s->free_slots.front();
      s->free_slots.pop_front();
      b = s->claim++;
      gen = s->generation;
    }
    fill_batch(s, b, s->slots[slot_id].buf.data());
    {
      std::lock_guard<std::mutex> lk(s->mu);
      if (gen == s->generation && !s->stop) {
        s->slots[slot_id].batch_idx = b;
        s->ready.emplace_back(b, slot_id);
        s->cv_ready.notify_all();
      } else {  // stale work from before a seek — recycle the slot
        s->free_slots.push_back(slot_id);
        s->cv_free.notify_one();
      }
    }
  }
}

}  // namespace

extern "C" {

void* dio_corpus_open(const char* path, int token_bytes) {
  if (token_bytes != 2 && token_bytes != 4 && token_bytes != 8) return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < token_bytes) {
    close(fd);
    return nullptr;
  }
  void* p = mmap(nullptr, (size_t)st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (p == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  madvise(p, (size_t)st.st_size, MADV_WILLNEED);
  Corpus* c = new Corpus();
  c->fd = fd;
  c->data = (const uint8_t*)p;
  c->filesize = (size_t)st.st_size;
  c->token_bytes = token_bytes;
  c->ntokens = (int64_t)(st.st_size / token_bytes);
  return c;
}

long long dio_corpus_len(void* h) { return h ? ((Corpus*)h)->ntokens : 0; }

void dio_corpus_close(void* h) {
  if (!h) return;
  Corpus* c = (Corpus*)h;
  munmap((void*)c->data, c->filesize);
  close(c->fd);
  delete c;
}

// Deterministic infinite batch stream over a corpus.
void* dio_stream_create(void* corpus, long long seq_len, long long batch,
                        unsigned long long seed, int nthreads, int qdepth) {
  Corpus* c = (Corpus*)corpus;
  if (!c || seq_len <= 0 || batch <= 0) return nullptr;
  int64_t nwindows = (c->ntokens - 1) / seq_len;
  if (nwindows <= 0) return nullptr;
  if (nthreads < 1) nthreads = 1;
  if (qdepth < nthreads + 1) qdepth = nthreads + 1;
  Stream* s = new Stream();
  s->corpus = c;
  s->seq_len = seq_len;
  s->batch = batch;
  s->nwindows = nwindows;
  s->seed = seed;
  s->slots.resize(qdepth);
  for (int i = 0; i < qdepth; i++) {
    s->slots[i].buf.resize((size_t)batch * (seq_len + 1));
    s->free_slots.push_back(i);
  }
  for (int i = 0; i < nthreads; i++) s->workers.emplace_back(worker_main, s);
  return s;
}

long long dio_stream_nwindows(void* h) { return h ? ((Stream*)h)->nwindows : 0; }

// Blocking: fills out[batch * (seq_len+1)] (int32) with the next batch.
int dio_stream_next(void* h, int32_t* out) {
  Stream* s = (Stream*)h;
  if (!s) return 0;
  int slot_id = -1;
  {
    std::unique_lock<std::mutex> lk(s->mu);
    for (;;) {
      for (size_t i = 0; i < s->ready.size(); i++) {
        if (s->ready[i].first == s->next_emit) {
          slot_id = s->ready[i].second;
          s->ready.erase(s->ready.begin() + (long)i);
          break;
        }
      }
      if (slot_id >= 0 || s->stop) break;
      s->cv_ready.wait(lk);
    }
    if (slot_id < 0) return 0;
    s->next_emit++;
  }
  memcpy(out, s->slots[slot_id].buf.data(),
         (size_t)s->batch * (s->seq_len + 1) * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->free_slots.push_back(slot_id);
    s->cv_free.notify_one();
  }
  return 1;
}

// Checkpoint state: the absolute index of the next batch to be emitted.
long long dio_stream_state(void* h) {
  Stream* s = (Stream*)h;
  if (!s) return 0;
  std::lock_guard<std::mutex> lk(s->mu);
  return s->next_emit;
}

// Resume: restart the stream at absolute batch index `batch_idx`.
void dio_stream_seek(void* h, long long batch_idx) {
  Stream* s = (Stream*)h;
  if (!s) return;
  std::lock_guard<std::mutex> lk(s->mu);
  s->generation++;
  s->claim = batch_idx;
  s->next_emit = batch_idx;
  for (auto& pr : s->ready) {
    s->free_slots.push_back(pr.second);
  }
  s->ready.clear();
  s->cv_free.notify_all();
}

void dio_stream_destroy(void* h) {
  Stream* s = (Stream*)h;
  if (!s) return;
  {
    std::lock_guard<std::mutex> lk(s->mu);
    s->stop = true;
    s->cv_free.notify_all();
    s->cv_ready.notify_all();
  }
  for (auto& t : s->workers) t.join();
  delete s;
}

// Pure-function hook so tests can check permutation parity vs Python.
long long dio_feistel(long long idx, long long n, unsigned long long key) {
  return (long long)feistel_permute((uint64_t)idx, (uint64_t)n, key);
}

}  // extern "C"
