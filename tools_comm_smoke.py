#!/usr/bin/env python
"""Gradient-communication microbench: GPT-mini data-parallel step on the
8-virtual-device CPU mesh, one line per schedule.

Compares the default GSPMD schedule's explicit replacement
(distributed/grad_comm.py) across {allreduce-fp32, rs/ag-fp32, rs/ag-bf16,
rs/ag-int8}: step time, per-step wire bytes (reduce vs gather, from
profiler.comm_counters()), collective and bucket counts.

  python tools_comm_smoke.py [--iters N] [--warmup W] [--layers L] \
      [--hidden H] [--batch B] [--seq S] [--bucket-kb KB]

Prints, machine-greppable for the BENCH trajectory:

  COMM_SMOKE <name>: <ms>/step  reduce <MB>MB  gather <MB>MB  \
      collectives <n>  buckets <n>  fill <pct>%  loss <x>
  COMM_SMOKE ratio: rs/ag reduce bytes = <x> of allreduce
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


CONFIGS = [
    ("allreduce-fp32", {"FLAGS_grad_comm": "on",
                        "FLAGS_weight_update_sharding": False,
                        "FLAGS_allreduce_dtype": "float32"}),
    ("rs/ag-fp32", {"FLAGS_grad_comm": "on",
                    "FLAGS_weight_update_sharding": True,
                    "FLAGS_allreduce_dtype": "float32"}),
    ("rs/ag-bf16", {"FLAGS_grad_comm": "on",
                    "FLAGS_weight_update_sharding": True,
                    "FLAGS_allreduce_dtype": "bfloat16"}),
    ("rs/ag-int8", {"FLAGS_grad_comm": "on",
                    "FLAGS_weight_update_sharding": True,
                    "FLAGS_allreduce_dtype": "int8"}),
]


def run_config(name, flags, args):
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, gpt_loss_fn

    paddle.set_flags({"FLAGS_grad_comm": "auto",
                      "FLAGS_weight_update_sharding": False,
                      "FLAGS_allreduce_dtype": "float32",
                      "FLAGS_grad_bucket_bytes": args.bucket_kb * 1024})
    paddle.set_flags(flags)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=4,
                    max_seq_len=args.seq, compute_dtype="float32",
                    use_flash=False, remat=False, dropout=0.0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters())
    step = paddle.jit.TrainStep(model, gpt_loss_fn, opt, mesh=mesh)

    rng = np.random.RandomState(0)

    def batch():
        ids = rng.randint(0, cfg.vocab_size,
                          (args.batch, args.seq)).astype(np.int64)
        return paddle.to_tensor(ids)

    for _ in range(args.warmup):
        b = batch()
        loss = step(b, b)
    jax.block_until_ready(loss._data)

    profiler.reset_comm_counters()
    t0 = time.perf_counter()
    for _ in range(args.iters):
        b = batch()
        loss = step(b, b)
    jax.block_until_ready(loss._data)
    dt = (time.perf_counter() - t0) / args.iters
    c = profiler.comm_counters()
    per = lambda k: c[k] / max(c["steps"], 1)  # noqa: E731
    print(f"COMM_SMOKE {name}: {dt * 1e3:.1f}ms/step  "
          f"reduce {per('reduce_bytes') / 1e6:.2f}MB  "
          f"gather {per('gather_bytes') / 1e6:.2f}MB  "
          f"collectives {per('collectives'):.0f}  "
          f"buckets {per('buckets'):.0f}  "
          f"fill {c['bucket_fill'] * 100:.1f}%  "
          f"loss {float(loss.numpy()):.4f}")
    dist_env.set_mesh(None)
    return {"name": name, "ms": dt * 1e3,
            "reduce_bytes": per("reduce_bytes"),
            "gather_bytes": per("gather_bytes")}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--bucket-kb", type=int, default=16 * 1024)
    args = ap.parse_args()

    results = [run_config(name, flags, args) for name, flags in CONFIGS]
    by = {r["name"]: r for r in results}
    ratio = by["rs/ag-fp32"]["reduce_bytes"] / by["allreduce-fp32"]["reduce_bytes"]
    print(f"COMM_SMOKE ratio: rs/ag reduce bytes = {ratio:.2f} of allreduce")


if __name__ == "__main__":
    main()
