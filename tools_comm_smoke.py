#!/usr/bin/env python
"""Gradient-communication microbench: GPT-mini data-parallel step on the
8-virtual-device CPU mesh, one line per schedule.

Compares the default GSPMD schedule's explicit replacement
(distributed/grad_comm.py) across {allreduce-fp32, rs/ag-fp32, rs/ag-bf16,
rs/ag-int8}: step time, per-step wire bytes (reduce vs gather, from
profiler.comm_counters()), collective and bucket counts.

  python tools_comm_smoke.py [--iters N] [--warmup W] [--layers L] \
      [--hidden H] [--batch B] [--seq S] [--bucket-kb KB]

Prints, machine-greppable for the BENCH trajectory:

  COMM_SMOKE <name>: <ms>/step  reduce <MB>MB  gather <MB>MB  \
      collectives <n>  buckets <n>  fill <pct>%  loss <x>
  COMM_SMOKE ratio: rs/ag reduce bytes = <x> of allreduce

``--pp`` runs the pipeline-parallel backend ladder instead (gspmd vs
FLAGS_comm_backend='pp=ring' vs 'pp=fused' on a pp=4 mesh): per-rung
``COMM_SMOKE pp/<backend>`` lines with boundary MB / ppermute hops /
bubble %%, and the ring-over-gspmd speedup ratio (``--deterministic``
for the tiny parity-only tier-1 sub-rung).
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


CONFIGS = [
    ("allreduce-fp32", {"FLAGS_grad_comm": "on",
                        "FLAGS_weight_update_sharding": False,
                        "FLAGS_allreduce_dtype": "float32"}),
    ("rs/ag-fp32", {"FLAGS_grad_comm": "on",
                    "FLAGS_weight_update_sharding": True,
                    "FLAGS_allreduce_dtype": "float32"}),
    ("rs/ag-bf16", {"FLAGS_grad_comm": "on",
                    "FLAGS_weight_update_sharding": True,
                    "FLAGS_allreduce_dtype": "bfloat16"}),
    ("rs/ag-int8", {"FLAGS_grad_comm": "on",
                    "FLAGS_weight_update_sharding": True,
                    "FLAGS_allreduce_dtype": "int8"}),
]


def run_config(name, flags, args):
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, gpt_loss_fn

    paddle.set_flags({"FLAGS_grad_comm": "auto",
                      "FLAGS_weight_update_sharding": False,
                      "FLAGS_allreduce_dtype": "float32",
                      "FLAGS_grad_bucket_bytes": args.bucket_kb * 1024})
    paddle.set_flags(flags)
    mesh = dist_env.create_hybrid_mesh(dp=8)
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=4,
                    max_seq_len=args.seq, compute_dtype="float32",
                    use_flash=False, remat=False, dropout=0.0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(3e-4, parameters=model.parameters())
    step = paddle.jit.TrainStep(model, gpt_loss_fn, opt, mesh=mesh)

    rng = np.random.RandomState(0)

    def batch():
        ids = rng.randint(0, cfg.vocab_size,
                          (args.batch, args.seq)).astype(np.int64)
        return paddle.to_tensor(ids)

    for _ in range(args.warmup):
        b = batch()
        loss = step(b, b)
    jax.block_until_ready(loss._data)

    profiler.reset_comm_counters()
    t0 = time.perf_counter()
    for _ in range(args.iters):
        b = batch()
        loss = step(b, b)
    jax.block_until_ready(loss._data)
    dt = (time.perf_counter() - t0) / args.iters
    c = profiler.comm_counters()
    per = lambda k: c[k] / max(c["steps"], 1)  # noqa: E731
    print(f"COMM_SMOKE {name}: {dt * 1e3:.1f}ms/step  "
          f"reduce {per('reduce_bytes') / 1e6:.2f}MB  "
          f"gather {per('gather_bytes') / 1e6:.2f}MB  "
          f"collectives {per('collectives'):.0f}  "
          f"buckets {per('buckets'):.0f}  "
          f"fill {c['bucket_fill'] * 100:.1f}%  "
          f"loss {float(loss.numpy()):.4f}")
    dist_env.set_mesh(None)
    return {"name": name, "ms": dt * 1e3,
            "reduce_bytes": per("reduce_bytes"),
            "gather_bytes": per("gather_bytes")}


def _pp_case(backend, pp, layers, hidden, batch, seq, M, iters, warmup,
             wire="auto"):
    """One rung: jitted value_and_grad of a GPT-block run_pipeline on a
    single-axis pp mesh (the GSPMD schedule compiles there on the CPU
    harness; the hybrid dp x pp mesh trips a pre-existing PartitionId
    limitation of SPMD CPU partitioning)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed import comm_backend as cb
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.distributed import pipeline as pl
    from paddle_tpu.models.gpt import GPTConfig, gpt_block_fn
    from paddle_tpu.models.gpt_hybrid import gpt_param_specs, init_gpt_params

    paddle.set_flags({"FLAGS_comm_backend":
                      "" if backend == "gspmd" else f"pp={backend}",
                      "FLAGS_pp_wire_dtype": wire})
    mesh = dist_env.create_single_axis_mesh("pp", pp)
    cfg = GPTConfig(vocab_size=64, hidden_size=hidden, num_layers=layers,
                    num_heads=4, max_seq_len=seq, use_flash=False,
                    compute_dtype="float32", pp_schedule="gpipe")
    params = init_gpt_params(cfg, jax.random.key(0))["blocks"]
    x = jax.random.normal(jax.random.key(1), (batch, seq, hidden))
    block = gpt_block_fn(cfg)
    kw = {}
    ppc = None
    if backend != "gspmd":
        from paddle_tpu.models.gpt import gpt_fused_boundary
        from paddle_tpu.ops.pallas_kernels import fused_collectives as fc
        specs = {k: P(*(a if (a is None or a in mesh.axis_names) else None
                        for a in tuple(s)))
                 for k, s in gpt_param_specs(cfg, pp=pp)["blocks"].items()}
        ppc = cb.resolve_pp(cfg, mesh, batch=batch, num_microbatches=M)
        kw = dict(backend=backend, pp_param_specs=specs,
                  x_spec=P(None, None, None),
                  wire_dtype=ppc.wire_dtype if ppc is not None else None)
        if backend == "fused":
            kw["boundary"] = gpt_fused_boundary(
                cfg, fc.meta_for(mesh, "pp"),
                fc.supported(mesh, shapes=(hidden,))[0])

    def loss(p, xx):
        return jnp.mean(pl.run_pipeline(block, p, xx, M, mesh=mesh,
                                        schedule="gpipe", **kw) ** 2)

    g = jax.jit(jax.value_and_grad(loss))
    with mesh:
        for _ in range(max(1, warmup)):
            l, grads = g(params, x)
        jax.block_until_ready(grads)
        t0 = time.perf_counter()
        for _ in range(iters):
            l, grads = g(params, x)
        jax.block_until_ready(grads)
    dt = (time.perf_counter() - t0) / max(iters, 1)
    c = {}
    if ppc is not None:
        pl.reset_pp_counters()
        for _ in range(iters):
            pl.record_pp_step(
                pl.gpt_pp_step_record(cfg, ppc, batch, seq, M, S=pp))
        c = pl.pp_counters()
    dist_env.set_mesh(None)
    paddle.set_flags({"FLAGS_comm_backend": "", "FLAGS_pp_wire_dtype": "auto"})
    return float(l), dt * 1e3, c


def run_pp_ladder(deterministic=False, pp=4, iters=None, warmup=2):
    """Pipeline-parallel backend ladder: gspmd vs ring vs ring/bf16-wire
    vs fused, one greppable COMM_SMOKE line per rung plus two ratios —
    boundary wire bytes (the explicit schedule's partial-send bf16 wire
    vs the fp32 boundary the GSPMD schedule sends, Paddle's
    ``enable_partial_send_recv`` analog; gated >= 1.15x by the slow test)
    and wall-clock ring-over-gspmd (a regression guard only on this CPU
    harness: the 8 'devices' are threads on shared cores, so the overlap
    win is a TPU property — tools_mfu_sweep's pp rung measures it there).

    ``deterministic=True`` is the tier-1 sub-rung: a tiny config, parity
    and wire-ratio gates only (no timing gates — CI timing is noise).
    """
    if deterministic:
        layers, hidden, batch, seq, M = pp, 32, 8, 16, 4
        iters = iters or 1
    else:
        layers, hidden, batch, seq, M = pp, 64, 32, 64, 16
        iters = iters or 8
    out = {"ok": True, "pp": pp}
    res = {}
    bytes_per_step = {}
    for name, backend, wire in (("gspmd", "gspmd", "auto"),
                                ("ring", "ring", "auto"),
                                ("ring/bf16-wire", "ring", "bfloat16"),
                                ("fused", "fused", "auto")):
        try:
            l, ms, c = _pp_case(backend, pp, layers, hidden, batch, seq, M,
                                iters, warmup, wire=wire)
        except Exception as e:  # noqa: BLE001
            print(f"COMM_SMOKE pp/{name}: FAILED {str(e)[:160]}", flush=True)
            out["ok"] = False
            continue
        res[name] = (l, ms)
        extra = ""
        if c:
            steps = max(c["steps"], 1)
            bytes_per_step[name] = c["boundary_bytes"] / steps
            extra = (f"  boundary {c['boundary_bytes'] / steps / 1e6:.3f}MB"
                     f"  hops {c['ppermute_hops'] // steps}"
                     f"  bubble {c['bubble_fraction'] * 100:.0f}%")
        print(f"COMM_SMOKE pp/{name}: {ms:.1f}ms/step  loss {l:.6f}{extra}",
              flush=True)
    if len(res) == 4:
        lg = res["gspmd"][0]
        parity = (abs(res["ring"][0] - lg) <= 1e-5 * max(abs(lg), 1e-12)
                  and abs(res["fused"][0] - res["ring"][0])
                  <= 1e-6 * max(abs(res["ring"][0]), 1e-12)
                  and abs(res["ring/bf16-wire"][0] - lg)
                  <= 1e-2 * max(abs(lg), 1e-12))
        speedup = res["gspmd"][1] / max(res["ring"][1], 1e-9)
        # the GSPMD schedule has no partial-send wire: its boundary is the
        # same fp32 hop the fp32-wire ring schedule sends (the ledger
        # measures the rung that actually ran)
        wire_ratio = (bytes_per_step.get("ring", 0.0)
                      / max(bytes_per_step.get("ring/bf16-wire", 1e-9), 1e-9))
        out.update(parity=parity, speedup=round(speedup, 3),
                   wire_ratio=round(wire_ratio, 3),
                   gspmd_ms=round(res["gspmd"][1], 2),
                   ring_ms=round(res["ring"][1], 2),
                   fused_ms=round(res["fused"][1], 2))
        out["ok"] = out["ok"] and parity and wire_ratio >= 1.15
        print(f"COMM_SMOKE pp ratio: partial-send wire bytes = "
              f"{1 / max(wire_ratio, 1e-9):.2f}x of the gspmd fp32 boundary "
              f"({wire_ratio:.2f}x reduction); ring wall-clock = "
              f"{speedup:.2f}x over gspmd", flush=True)
    else:
        out["ok"] = False
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--bucket-kb", type=int, default=16 * 1024)
    ap.add_argument("--pp", action="store_true",
                    help="run the pipeline-parallel backend ladder instead")
    ap.add_argument("--deterministic", action="store_true",
                    help="tiny parity-only pp ladder (the tier-1 sub-rung)")
    args = ap.parse_args()

    if args.pp:
        run_pp_ladder(deterministic=args.deterministic)
        return

    results = [run_config(name, flags, args) for name, flags in CONFIGS]
    by = {r["name"]: r for r in results}
    ratio = by["rs/ag-fp32"]["reduce_bytes"] / by["allreduce-fp32"]["reduce_bytes"]
    print(f"COMM_SMOKE ratio: rs/ag reduce bytes = {ratio:.2f} of allreduce")


if __name__ == "__main__":
    main()
