#!/usr/bin/env python
"""Long-context evidence on the real chip: pallas flash attention fwd+bwd
at S=8k/16k/32k, single chip (the sp>1 ring path is validated on the
virtual mesh in dryrun_multichip; this measures the per-chip kernel the
ring schedule runs between ppermute steps).

Prints one line per config; append winners to TPU_SMOKE.log.
"""
import sys
import time

sys.path.insert(0, "/root/repo")


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas_kernels.flash_attention import (
        flash_attention_bshd)

    assert jax.default_backend() == "tpu", jax.devices()
    H, D = 16, 64  # GPT-1.3B head geometry

    for S, B in ((8192, 4), (16384, 2), (32768, 1)):
        try:
            ks = jax.random.split(jax.random.key(0), 3)
            q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
                       for kk in ks)

            def loss(q, k, v):
                return flash_attention_bshd(
                    q, k, v, causal=True).astype(jnp.float32).sum()

            g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
            val, grads = g(q, k, v)
            jax.device_get(val)
            steps = 5
            t0 = time.perf_counter()
            for _ in range(steps):
                val, grads = g(q, k, v)
            jax.device_get(val)
            dt = (time.perf_counter() - t0) / steps
            # causal attention FLOPs: fwd 2*2*B*H*S^2/2*D, bwd ~2.5x fwd
            fl = 3.5 * 2 * B * H * (S * S / 2) * D * 2
            print(f"FLASH-LONG S={S} B={B}: fwd+bwd {dt*1e3:.1f} ms, "
                  f"~{fl/dt/1e12:.1f} TF/s, peak-mem-free", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"FLASH-LONG S={S}: FAILED {str(e)[:200]}", flush=True)
        finally:
            import gc
            gc.collect()
            for a in jax.live_arrays():
                try:
                    a.delete()
                except Exception:  # noqa: BLE001
                    pass
            jax.clear_caches()


if __name__ == "__main__":
    main()
