#!/usr/bin/env python
"""MFU sweep for ResNet-50 and BERT-base on the real chip (VERDICT r4 #4).

Runs a matrix of configs and prints one line per result; append the winners
to TPU_SMOKE.log. Designed for a flaky tunnel: every config is independent,
results stream as they finish, and the script never kills a TPU claim.

  python tools_mfu_sweep.py resnet   # layout x dtype x batch sweep
  python tools_mfu_sweep.py bert     # seq/batch sweep with flash attn
  python tools_mfu_sweep.py flash    # pallas flash-attn tile sweep (GPT)
  python tools_mfu_sweep.py tp       # mp comm-schedule ladder, gpt3-1.3B
  python tools_mfu_sweep.py tp67 [B] # same ladder on gpt3-6.7B (ROADMAP
                                     # MFU rung; sweeps FLAGS_comm_backend
                                     # gspmd/ring/fused alongside the tp
                                     # flags)
  python tools_mfu_sweep.py pp [B]   # pipeline comm-backend ladder on a
                                     # dp x pp mesh (FLAGS_comm_backend=
                                     # 'pp=gspmd|ring|fused' + bf16 wire)
                                     # with a bubble-fraction column
"""
from __future__ import annotations

import sys
import time

import numpy as np


def _sync(x):
    import jax
    jax.device_get(jax.tree_util.tree_leaves(x)[0])


def _peak():
    # single-source FLOP/MFU estimators (paddle_tpu/observability/flops.py)
    # — shared with bench.py and the live step telemetry, so sweep numbers
    # and live MFU cannot diverge
    import jax
    from paddle_tpu.observability.flops import peak_flops_bf16
    return peak_flops_bf16(getattr(jax.devices()[0], "device_kind", ""))


def resnet_case(batch, data_format, dtype, steps=20):
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    paddle.seed(0)
    model = paddle.vision.models.resnet50(num_classes=1000,
                                          data_format=data_format)
    opt = paddle.optimizer.Momentum(0.1, parameters=model.parameters())
    if dtype == "bf16":
        model, opt = paddle.amp.decorate(model, opt, level="O2",
                                         dtype="bfloat16")
    step = paddle.jit.TrainStep(model, nn.CrossEntropyLoss(), opt)
    import jax.numpy as jnp
    shape = (batch, 3, 224, 224) if data_format == "NCHW" \
        else (batch, 224, 224, 3)
    x_np = np.random.RandomState(0).rand(*shape).astype(np.float32)
    x = paddle.to_tensor(x_np)
    if dtype == "bf16":
        # activations must ENTER as bf16: conv casts weights UP to the
        # activation dtype, so fp32 input would silently run fp32 convs
        x = paddle.to_tensor(jnp.asarray(x_np, jnp.bfloat16))
    y = paddle.to_tensor(np.random.RandomState(1).randint(
        0, 1000, (batch, 1)).astype(np.int64))
    loss = step(x, y)          # compile
    _sync(loss._data)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(x, y)
    _sync(loss._data)
    dt = (time.perf_counter() - t0) / steps
    img_s = batch / dt
    # ResNet-50 fwd ~4.1 GFLOPs/img @224; x3 for training
    mfu = img_s * 4.1e9 * 3 / _peak()
    print(f"RESNET50 {data_format} {dtype} bs{batch}: {img_s:.0f} img/s, "
          f"{dt * 1e3:.1f} ms/step, MFU {mfu * 100:.1f}%, "
          f"loss {float(np.asarray(loss.numpy())):.3f}", flush=True)


def bert_case(batch, seq, use_flash, steps=15, tiny=False):
    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertForPretraining, BertConfig

    cfg = BertConfig() if not tiny else BertConfig(
        vocab_size=512, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128)
    # BertConfig has no use_flash field; the SDPA routing honors the
    # global flag (nn/functional/attention.py:105)
    paddle.set_flags({"FLAGS_use_flash_attention": use_flash})
    paddle.seed(0)
    net = BertForPretraining(cfg)
    opt = paddle.optimizer.AdamW(1e-4)
    net, opt = paddle.amp.decorate(net, opt, level="O2", dtype="bfloat16")
    # fused head+CE path: the [B, S, 30k] logits buffer of the plain
    # loss(forward()) OOMs the 16G chip at bs64 seq512

    class _Fused(paddle.nn.Layer):
        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, ids, labels):
            return self.inner.pretraining_loss(ids, labels)

    step = paddle.jit.TrainStep(_Fused(net), lambda out: out, opt)
    ids = paddle.to_tensor(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    labels = paddle.to_tensor(np.random.RandomState(1).randint(
        0, cfg.vocab_size, (batch, seq)).astype(np.int64))
    loss = step((ids, labels), ())
    _sync(loss._data)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step((ids, labels), ())
    _sync(loss._data)
    dt = (time.perf_counter() - t0) / steps
    tok_s = batch * seq / dt
    from paddle_tpu.observability.flops import dense_flops_per_token
    n_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    mfu = tok_s * dense_flops_per_token(n_params) / _peak()
    print(f"BERT bs{batch} seq{seq} flash={use_flash}: "
          f"{tok_s:.0f} tok/s, {dt * 1e3:.1f} ms/step, "
          f"MFU {mfu * 100:.1f}%, loss "
          f"{float(np.asarray(loss.numpy())):.3f}", flush=True)


def gpt_flash_tiles(model_name="gpt3-1.3B", batch=8, seq=2048, steps=8):
    """Sweep pallas flash-attention tile sizes on the flagship config —
    the single-chip GPT MFU autotune surface (flash_block_q/k)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT_CONFIGS
    from paddle_tpu.models.gpt_hybrid import HybridTrainStep

    for bq, bk in ((256, 256), (512, 256), (256, 512), (512, 512),
                   (1024, 256), (128, 128)):
        try:
            cfg = GPT_CONFIGS[model_name]
            cfg.max_seq_len = max(cfg.max_seq_len, seq)
            cfg.use_flash = True
            cfg.compute_dtype = "bfloat16"
            cfg.remat = True
            cfg.flash_block_q, cfg.flash_block_k = bq, bk
            opt = paddle.optimizer.AdamW(
                2e-4, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0),
                moment_dtype="bfloat16")
            step = HybridTrainStep(cfg, opt, param_dtype=jnp.bfloat16)
            ids = jax.random.randint(jax.random.key(0), (batch, seq), 0,
                                     cfg.vocab_size, jnp.int32)
            loss = step(ids)
            _sync(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = step(ids)
            _sync(loss)
            dt = (time.perf_counter() - t0) / steps
            tok_s = batch * seq / dt
            from paddle_tpu.observability.flops import model_flops_per_token
            fpt, _ = model_flops_per_token(cfg, seq)
            print(f"FLASH {model_name} bq{bq} bk{bk}: {tok_s:.0f} tok/s, "
                  f"{dt:.3f} s/step, MFU {tok_s * fpt / _peak() * 100:.1f}%",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"FLASH bq{bq} bk{bk}: FAILED {str(e)[:140]}", flush=True)
        finally:
            import gc
            gc.collect()
            for a in jax.live_arrays():
                try:
                    a.delete()
                except Exception:  # noqa: BLE001
                    pass
            jax.clear_caches()


def gpt_tp_schedules(model_name="gpt3-1.3B", batch=8, seq=2048, steps=8,
                     mp=None):
    """Sweep the tensor-parallel schedule (FLAGS_sequence_parallel /
    FLAGS_mp_overlap / FLAGS_comm_backend) on a multi-chip mp mesh — the
    GSPMD-vs-explicit-vs-fused ladder of tools_tp_smoke.py at real-chip
    scale, reported as MFU. `tp67` runs it on the gpt3-6.7B config (the
    ROADMAP MFU rung: target >=45% at 6.7B)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.models.gpt import GPT_CONFIGS
    from paddle_tpu.models.gpt_hybrid import HybridTrainStep

    mp = mp or jax.device_count()
    ladder = (("gspmd", {}),
              ("seqpar", {"FLAGS_sequence_parallel": True}),
              ("seqpar+overlap", {"FLAGS_sequence_parallel": True,
                                  "FLAGS_mp_overlap": True}),
              ("ring-backend", {"FLAGS_comm_backend": "mp=ring"}),
              ("fused-backend", {"FLAGS_comm_backend": "mp=fused"}),
              ("fused-mp+ring-dp", {"FLAGS_comm_backend":
                                    "mp=fused,dp=ring"}))
    for name, flags in ladder:
        try:
            paddle.set_flags({"FLAGS_sequence_parallel": False,
                              "FLAGS_mp_overlap": False,
                              "FLAGS_comm_backend": ""})
            paddle.set_flags(flags)
            profiler.reset_mp_comm_counters()
            mesh = dist_env.create_hybrid_mesh(dp=-1, mp=mp)
            cfg = GPT_CONFIGS[model_name]
            cfg.max_seq_len = max(cfg.max_seq_len, seq)
            cfg.use_flash = True
            cfg.compute_dtype = "bfloat16"
            cfg.remat = True
            opt = paddle.optimizer.AdamW(
                2e-4, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
            step = HybridTrainStep(cfg, opt, mesh=mesh,
                                   param_dtype=jnp.bfloat16)
            ids = jax.random.randint(jax.random.key(0), (batch, seq), 0,
                                     cfg.vocab_size, jnp.int32)
            loss = step(ids)
            _sync(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = step(ids)
            _sync(loss)
            dt = (time.perf_counter() - t0) / steps
            tok_s = batch * seq / dt
            from paddle_tpu.observability.flops import model_flops_per_token
            fpt, _ = model_flops_per_token(cfg, seq)
            peak = _peak() * jax.device_count()
            print(f"TP {model_name} mp{mp} {name}: {tok_s:.0f} tok/s, "
                  f"{dt:.3f} s/step, MFU {tok_s * fpt / peak * 100:.1f}%  "
                  f"[{profiler.mp_comm_summary()}]", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"TP {name}: FAILED {str(e)[:160]}", flush=True)
        finally:
            dist_env.set_mesh(None)


def gpt_pp_schedules(model_name="gpt3-1.3B", batch=8, seq=2048, steps=8,
                     pp=None, microbatches=8):
    """Sweep the pipeline-parallel comm backend (FLAGS_comm_backend=
    'pp=gspmd|ring|fused') on a dp x pp mesh — GSPMD's masked-select
    schedule vs the explicit overlapped ring schedule vs the fused
    last-GEMM RDMA boundary — reported as MFU plus the pp ledger's
    boundary traffic and bubble-fraction estimate per rung."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.models.gpt import GPT_CONFIGS
    from paddle_tpu.models.gpt_hybrid import HybridTrainStep

    pp = pp or min(4, jax.device_count())
    ladder = (("gspmd", {"FLAGS_comm_backend": ""}),
              ("ring", {"FLAGS_comm_backend": "pp=ring"}),
              ("ring+bf16-wire", {"FLAGS_comm_backend": "pp=ring",
                                  "FLAGS_pp_wire_dtype": "bfloat16"}),
              ("fused", {"FLAGS_comm_backend": "pp=fused"}))
    for name, flags in ladder:
        try:
            paddle.set_flags({"FLAGS_sequence_parallel": False,
                              "FLAGS_mp_overlap": False,
                              "FLAGS_comm_backend": "",
                              "FLAGS_pp_wire_dtype": "auto"})
            paddle.set_flags(flags)
            profiler.reset_pp_comm_counters()
            mesh = dist_env.create_hybrid_mesh(dp=-1, pp=pp)
            cfg = GPT_CONFIGS[model_name]
            cfg.max_seq_len = max(cfg.max_seq_len, seq)
            cfg.use_flash = True
            cfg.compute_dtype = "bfloat16"
            cfg.remat = True
            opt = paddle.optimizer.AdamW(
                2e-4, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
            step = HybridTrainStep(cfg, opt, mesh=mesh,
                                   num_microbatches=microbatches,
                                   param_dtype=jnp.bfloat16)
            ids = jax.random.randint(jax.random.key(0), (batch, seq), 0,
                                     cfg.vocab_size, jnp.int32)
            loss = step(ids)
            _sync(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = step(ids)
            _sync(loss)
            dt = (time.perf_counter() - t0) / steps
            tok_s = batch * seq / dt
            from paddle_tpu.observability.flops import model_flops_per_token
            fpt, _ = model_flops_per_token(cfg, seq)
            peak = _peak() * jax.device_count()
            c = profiler.pp_comm_counters()
            per_step = max(c["steps"], 1)
            print(f"PP {model_name} pp{pp} M{microbatches} {name}: "
                  f"{tok_s:.0f} tok/s, {dt:.3f} s/step, "
                  f"MFU {tok_s * fpt / peak * 100:.1f}%  "
                  f"boundary {c['boundary_bytes'] / per_step / 1e6:.2f}MB  "
                  f"hops {c['ppermute_hops'] // per_step}  "
                  f"fused {c['fused_dispatches'] // per_step}  "
                  f"bubble {c['bubble_fraction'] * 100:.1f}%",
                  flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"PP {name}: FAILED {str(e)[:160]}", flush=True)
        finally:
            dist_env.set_mesh(None)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "resnet"
    if which == "flash":
        gpt_flash_tiles()
        return
    if which == "tp":
        gpt_tp_schedules()
        return
    if which == "pp":
        # pipeline comm-backend ladder (PR 18): gspmd vs explicit ring
        # (plus bf16 partial-send wire) vs fused boundary, with the
        # ledger's bubble-fraction column; argv[2] overrides the batch
        batch = int(sys.argv[2]) if len(sys.argv) > 2 else 8
        gpt_pp_schedules(batch=batch)
        return
    if which == "tp67":
        # the ROADMAP 6.7B MFU rung: gspmd/ring/fused comm-backend ladder
        # on the flagship config (batch trimmed for the per-chip memory of
        # an mp-sharded 6.7B; bump with argv[2] on bigger slices)
        batch = int(sys.argv[2]) if len(sys.argv) > 2 else 4
        gpt_tp_schedules("gpt3-6.7B", batch=batch, seq=2048)
        return
    if which == "resnet":
        # big batches first: ~10-15 ms/step of the 62 ms bs128 step is RPC
        # arg marshaling (TPU_SMOKE round-5 breakdown), so bs512 amortizes
        for df in ("NHWC", "NCHW"):
            for dtype in ("bf16",):
                for bs in (512, 256, 128):
                    try:
                        resnet_case(bs, df, dtype)
                    except Exception as e:  # noqa: BLE001
                        print(f"RESNET50 {df} {dtype} bs{bs}: FAILED "
                              f"{str(e)[:160]}", flush=True)
    else:
        for bs, seq in ((64, 512), (128, 256), (32, 512)):
            for flash in (True, False):
                try:
                    bert_case(bs, seq, flash)
                except Exception as e:  # noqa: BLE001
                    print(f"BERT bs{bs} seq{seq} flash={flash}: FAILED "
                          f"{str(e)[:160]}", flush=True)


if __name__ == "__main__":
    main()
