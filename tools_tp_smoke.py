#!/usr/bin/env python
"""Tensor-parallel schedule microbench: GPT-mini on the 8-virtual-device CPU
mesh (mp-dominant), one line per schedule rung.

Ladder: GSPMD baseline (two blocking all-reduces per block, replicated
activations) vs sequence parallelism (RS+AG, 1/mp activations between
blocks) vs the ring backend (mp-1 ppermute hops per collective, chunk
GEMMs issued on arrival) vs the fused backend (Pallas GEMM+collective
kernels: in-kernel remote DMA, no HBM gather buffer, zero XLA-level
ppermute) — distributed/tp_overlap.py + ops/pallas_kernels/
fused_collectives.py, selected via FLAGS_comm_backend.

NOTE the fused rung needs a single-named-axis mesh on CPU (interpret-mode
remote DMA); with --dp 1 (the default) the script builds one, so the whole
gspmd/ring/fused ladder runs. On CPU the fused rung's kernels execute in
interpret mode — its ms/step is a correctness rung there, not a perf
number; real-TPU timing comes from tools_mfu_sweep.py tp.

  python tools_tp_smoke.py [--iters N] [--warmup W] [--layers L] \
      [--hidden H] [--heads NH] [--batch B] [--seq S] [--mp MP] [--dp DP]

Prints, machine-greppable for the BENCH trajectory:

  TP_SMOKE <name>: <ms>/step  mp-wire <MB>MB  collectives <n>  hops <n>  \
      act-between-blocks <MB>MB  loss <x>
  TP_SMOKE ratio: seq-parallel activation bytes = <x> of baseline
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

if not os.environ.get("TP_SMOKE_REAL_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


LADDER = [
    ("gspmd-baseline", {}),
    ("seq-parallel", {"FLAGS_sequence_parallel": True}),
    ("seq-parallel+overlap", {"FLAGS_sequence_parallel": True,
                              "FLAGS_mp_overlap": True}),
    ("fused-kernels", {"FLAGS_comm_backend": "mp=fused"}),
]


def run_rung(name, flags, args):
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.distributed import tp_overlap as tp
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import HybridTrainStep

    paddle.set_flags({"FLAGS_sequence_parallel": False,
                      "FLAGS_mp_overlap": False,
                      "FLAGS_comm_backend": ""})
    paddle.set_flags(flags)
    profiler.reset_mp_comm_counters()
    if args.dp == 1:
        # single-named-axis mesh: what the fused rung's interpret-mode
        # kernels need on CPU (and harmless for the other rungs)
        mesh = dist_env.create_single_axis_mesh("mp", args.mp)
    else:
        mesh = dist_env.create_hybrid_mesh(dp=args.dp, mp=args.mp)
    cfg = GPTConfig(vocab_size=512, hidden_size=args.hidden,
                    num_layers=args.layers, num_heads=args.heads,
                    max_seq_len=args.seq, compute_dtype="float32",
                    use_flash=False, remat=True, dropout=0.0)
    opt = paddle.optimizer.AdamW(3e-4)
    step = HybridTrainStep(cfg, opt, mesh=mesh, seed=0)
    ids = jax.random.randint(jax.random.key(0), (args.batch, args.seq), 0,
                             cfg.vocab_size, jnp.int32)
    for _ in range(args.warmup):
        loss = step(ids)
    jax.block_until_ready(loss)

    profiler.reset_mp_comm_counters()
    t0 = time.perf_counter()
    for _ in range(args.iters):
        loss = step(ids)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.iters

    c = profiler.mp_comm_counters()
    if c["steps"]:
        per = lambda k: c[k] / c["steps"]  # noqa: E731
        wire = per("rs_bytes") + per("ag_bytes")
        coll, hops = per("collectives"), per("ppermute_hops")
        fused = per("fused_dispatches")
        act = c["activation_bytes"]
        backend = c["backend"].get("mp", "gspmd")
    else:  # GSPMD baseline: static ledger of the partitioner's schedule
        base = tp.gspmd_baseline_record(cfg, args.mp, args.batch, args.seq)
        wire = sum(base.bytes_by_kind.values())
        coll, hops, fused = base.collectives, 0, 0
        act = base.activation_bytes
        backend = "gspmd"
    print(f"TP_SMOKE {name}: {dt * 1e3:.1f}ms/step  backend {backend}  "
          f"mp-wire {wire / 1e6:.2f}MB  collectives {coll:.0f}  "
          f"hops {hops:.0f}  fused-dispatches {fused:.0f}  "
          f"act-between-blocks {act / 1e6:.3f}MB  "
          f"loss {float(np.asarray(jax.device_get(loss))):.4f}",
          flush=True)
    dist_env.set_mesh(None)
    return {"name": name, "ms": dt * 1e3, "wire": wire, "act": act}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--mp", type=int, default=8)
    ap.add_argument("--dp", type=int, default=1)
    args = ap.parse_args()

    results = [run_rung(name, flags, args) for name, flags in LADDER]
    by = {r["name"]: r for r in results}
    ratio = by["seq-parallel"]["act"] / by["gspmd-baseline"]["act"]
    print(f"TP_SMOKE ratio: seq-parallel activation bytes = {ratio:.3f} "
          f"of baseline (1/mp = {1 / args.mp:.3f})")


if __name__ == "__main__":
    main()
