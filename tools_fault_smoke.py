#!/usr/bin/env python
"""Fault-tolerance chaos bench: kill-and-resume + anomaly-guard smoke on
CPU (JAX_PLATFORMS=cpu), exercising the whole recovery stack end to end.

Legs (each seeded, deterministic):

  1. kill-resume     — train an MLP T steps (golden), rerun with a simulated
                       preemption at a pseudo-random step, resume from the
                       latest hardened checkpoint, assert the final params
                       are BITWISE equal to the uninterrupted run
  2. kill-resume-wus — same under FLAGS_weight_update_sharding + dp=8 mesh
                       + accumulate_steps=2 (packed dp-sharded slots)
  3. nan-skip        — poison one batch mid-run under
                       FLAGS_anomaly_policy=skip; assert the step was
                       skipped compiled-side (no host sync added) and the
                       final params are finite
  4. nan-rollback    — K consecutive poisoned batches under rollback;
                       assert the step restored the last checkpoint and
                       training finished finite
  5. io-chaos        — inject transient OSErrors into checkpoint writes and
                       corrupt the latest checkpoint on disk; assert saves
                       retried and restore quarantined + fell back

Serving chaos ladder (run_serving_ladder; the self-healing serving legs):

  6. serve-kill-resume     — abrupt engine kill mid-decode (FaultPlan.
                             kill_at_decode_step, nothing flushed); restore
                             from the last CADENCE snapshot, finish, assert
                             every request's tokens BITWISE equal the
                             uninterrupted run; reports p99 recovery latency
  7. serve-rolling-restart — ServingSupervisor drains+restarts each replica
                             mid-traffic; zero requests dropped, bitwise
  8. serve-snapshot-io     — OSError injected into the snapshot write
                             (retried through the hardened path) + rot the
                             newest snapshot on disk (quarantine + fallback
                             to the previous good one, still bitwise)
  9. serve-stale-heartbeat — one replica's heartbeats suppressed (frozen
                             process); the supervisor fails it over; zero
                             requests dropped, bitwise

Topology-elastic ladder (run_elastic_ladder; the mesh-reforming legs —
each seeded, injected chip loss, zero wall-clock dependence):

 10. elastic-kill-shrink-resume — dp=8 + weight-update sharding, a rank
                             is lost mid-run; the ElasticMeshSupervisor
                             re-forms dp=4 from the survivors and resumes
                             from the resharded snapshot with ZERO manual
                             steps; the resumed dp=4 trajectory is BITWISE
                             identical to an independent dp=4 step
                             restored from the same snapshot, and the
                             final params track the uninterrupted dp=8 run
                             within tolerance (reduce order differs)
 11. elastic-grow-back      — the lost rank returns; the supervisor grows
                             the mesh back to dp=8 (memoized executables
                             reused) and finishes within tolerance
 12. elastic-shrink-accum   — accumulate_steps=2 with the snapshot landing
                             MID accumulation window; the resharded
                             accumulator + micro counter continue the
                             window on the dp=4 mesh

Serving-elastic ladder (run_serving_elastic_ladder; chip-loss reform of
mp groups on mp-portable snapshots):

 13. serve-chip-kill-reform — 2 mp=2 groups on 4 devices; one chip dies,
                              the group re-forms over the survivor (mp=1)
                              from its last snapshot — zero drops,
                              bitwise, reform-latency p99 over trials
 14. serve-degraded-shed-grow-back — the degraded fleet sheds lowest-
                              class backlog with live retry hints, the
                              chip returns, the group grows back with
                              zero drops and ZERO new traces

Silent-data-corruption ladder (run_sdc_ladder; the integrity sentinel —
FLAGS_sdc_check_every fingerprints, peer repair, shadow audit, wire CRC):

 15. sdc-train-bitflip-repair — a mantissa flip on one replica's params
                              is caught by the fused cross-replica
                              fingerprint, localized by majority vote,
                              peer-repaired IN PLACE and the step
                              re-dispatched: final params bitwise equal
                              the fault-free run, zero disk restores,
                              and the verdict rides the guard's one
                              combined fetch (host_syncs == steps)
 16. sdc-train-quarantine   — two flips on the SAME rank cross the
                              repair-charge threshold; the elastic
                              supervisor's quarantine policy reports the
                              chip as LOST (reform, not rewind)
 17. sdc-serve-audit-catch  — FINITE KV corruption (exponent-bit flip)
                              the all-finite guard cannot see; the
                              sampled shadow audit catches the token
                              divergence and fails the replica over —
                              zero drops, bitwise
 18. sdc-kv-wire-crc        — a prefill->decode page payload corrupted
                              on the wire is refused by its CRC32 stamp;
                              the retained stream is re-offered and
                              seats bitwise
 19. sdc-ckpt-scrub         — bit rot in a retained snapshot is found by
                              the cadence scrub and quarantined to
                              *.corrupt before any restore needs it

  python tools_fault_smoke.py [--steps N] [--kill-step K] [--seed S]
                              [--skip-serving] [--skip-elastic]
                              [--skip-sdc]

Prints, machine-greppable:

  FAULT_SMOKE <leg>: <status>  <details>
"""
from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


DEFAULT_FLAGS = {
    "FLAGS_anomaly_policy": "off",
    "FLAGS_anomaly_max_bad_steps": 3,
    "FLAGS_grad_comm": "auto",
    "FLAGS_weight_update_sharding": False,
    "FLAGS_allreduce_dtype": "float32",
}


def build_step(paddle, nn, seed, flags=None, mesh=None, k=1):
    paddle.set_flags(dict(DEFAULT_FLAGS))
    if flags:
        paddle.set_flags(flags)
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(32, 64), nn.GELU(), nn.Dropout(0.1),
                      nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(0.01, parameters=m.parameters())
    return paddle.jit.TrainStep(m, nn.MSELoss(), opt, mesh=mesh,
                                accumulate_steps=k)


def make_data(steps, seed):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((steps, 16, 32)).astype(np.float32),
            rng.standard_normal((steps, 16, 8)).astype(np.float32))


def run(paddle, step, X, Y, lo=0, hi=None):
    hi = len(X) if hi is None else hi
    loss = None
    for i in range(lo, hi):
        loss = step(paddle.to_tensor(X[i]), paddle.to_tensor(Y[i]))
    return ({n: np.asarray(a) for n, a in step.params.items()},
            float(np.asarray(loss.numpy())) if loss is not None else None)


def leg_kill_resume(paddle, nn, fi, args, flags=None, mesh_fn=None, k=1,
                    name="kill-resume"):
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    X, Y = make_data(args.steps, args.seed)
    mesh = mesh_fn() if mesh_fn else None
    golden, gloss = run(paddle, build_step(paddle, nn, args.seed, flags,
                                           mesh, k), X, Y)

    # pseudo-random but seeded kill point, at least one checkpoint before it
    kill = args.kill_step or (3 + int(
        np.random.default_rng(args.seed).integers(args.steps - 4)))
    ckpt_dir = tempfile.mkdtemp(prefix="fault_smoke_")
    try:
        mesh = mesh_fn() if mesh_fn else None
        step_a = build_step(paddle, nn, args.seed, flags, mesh, k)
        mgr = CheckpointManager(ckpt_dir, async_save=False)
        step_a.attach_checkpoint(mgr, save_every=2)
        try:
            with fi.inject(fi.FaultPlan(preempt_at_step=kill)):
                run(paddle, step_a, X, Y)
            raise AssertionError("preemption did not fire")
        except fi.Preemption:
            pass
        del step_a

        mesh = mesh_fn() if mesh_fn else None
        step_b = build_step(paddle, nn, args.seed + 99, flags, mesh, k)
        step_b.load_state_dict(mgr.restore())
        resumed, rloss = run(paddle, step_b, X, Y, lo=step_b._step)
        for n in golden:
            np.testing.assert_array_equal(golden[n], resumed[n])
        assert rloss == gloss, (rloss, gloss)  # final loss bitwise too
        print(f"FAULT_SMOKE {name}: OK  killed@{kill} "
              f"resumed@{mgr.latest_step()} steps={args.steps} "
              f"final-loss={rloss:.6f} (golden {gloss:.6f}) bitwise-equal")
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def leg_nan_skip(paddle, nn, fi, args):
    from paddle_tpu.jit.train_step import (anomaly_counters,
                                           reset_anomaly_counters)
    X, Y = make_data(args.steps, args.seed)
    reset_anomaly_counters()
    step = build_step(paddle, nn, args.seed,
                      {"FLAGS_anomaly_policy": "skip"})
    poison = args.steps // 2
    with fi.inject(fi.FaultPlan(nan_at_steps=[poison])):
        params, loss = run(paddle, step, X, Y)
    c = anomaly_counters()
    assert c["bad_steps"] == 1 and c["skipped_updates"] == 1, c
    assert c["host_syncs"] == c["steps"], c  # zero extra syncs
    assert all(np.isfinite(v).all() for v in params.values())
    print(f"FAULT_SMOKE nan-skip: OK  poisoned@{poison} "
          f"skipped=1 host-syncs={c['host_syncs']}/{c['steps']} "
          f"final-loss={loss:.6f}")


def leg_nan_rollback(paddle, nn, fi, args):
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    from paddle_tpu.jit.train_step import (anomaly_counters,
                                           reset_anomaly_counters)
    X, Y = make_data(args.steps, args.seed)
    reset_anomaly_counters()
    step = build_step(paddle, nn, args.seed,
                      {"FLAGS_anomaly_policy": "rollback",
                       "FLAGS_anomaly_max_bad_steps": 2})
    ckpt_dir = tempfile.mkdtemp(prefix="fault_smoke_")
    try:
        mgr = CheckpointManager(ckpt_dir, async_save=False)
        step.attach_checkpoint(mgr, save_every=2)
        p = args.steps // 2
        with fi.inject(fi.FaultPlan(nan_at_steps=[p, p + 1])):
            params, loss = run(paddle, step, X, Y)
        c = anomaly_counters()
        assert c["rollbacks"] == 1, c
        assert all(np.isfinite(v).all() for v in params.values())
        print(f"FAULT_SMOKE nan-rollback: OK  poisoned@{p},{p + 1} "
              f"rollbacks=1 final-loss={loss:.6f}")
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def leg_io_chaos(paddle, fi, args):
    from paddle_tpu.incubate.checkpoint import (CheckpointManager,
                                                ckpt_counters)
    ckpt_dir = tempfile.mkdtemp(prefix="fault_smoke_")
    try:
        before = ckpt_counters()
        mgr = CheckpointManager(ckpt_dir, async_save=False, retries=3,
                                retry_backoff=0.01)
        with fi.inject(fi.FaultPlan(io_error_on_writes=[1, 3])):
            mgr.save(1, {"w": np.arange(16.0), "step": 1})
            mgr.save(2, {"w": np.full(16, 2.0), "step": 2})
        retries = ckpt_counters()["save_retries"] - before["save_retries"]
        # rot the newest step on disk
        with open(os.path.join(ckpt_dir, "step_2", "state.pdckpt"),
                  "r+b") as f:
            f.seek(-8, 2)
            f.write(b"\x00" * 8)
        got = mgr.restore()
        assert int(got["step"]) == 1, got
        quarantined = (ckpt_counters()["quarantined"]
                       - before["quarantined"])
        assert quarantined == 1
        print(f"FAULT_SMOKE io-chaos: OK  transient-errors=2 "
              f"retries={retries} corrupt-quarantined={quarantined} "
              f"fell-back-to=step_1")
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


# -- serving chaos ladder -----------------------------------------------------

_SERVING = None


def _serving_fixture():
    """Tiny GPT + helpers, built once (executables are memoized per config,
    so every leg reuses the same compiled fused step)."""
    global _SERVING
    if _SERVING is not None:
        return _SERVING
    import jax as _jax

    from paddle_tpu import serving
    from paddle_tpu.models.generation import generate_from_params
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_hybrid import init_gpt_params

    cfg = GPTConfig(vocab_size=97, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=128, dropout=0.0, use_flash=False,
                    compute_dtype="float32", remat=False)
    params = init_gpt_params(cfg, _jax.random.key(0))

    def factory():
        return serving.Engine(params=params, config=cfg, num_slots=3,
                              max_seq_len=96, page_size=8, prefill_chunk=8,
                              kv_layout="paged")

    def ref(prompt, n, **kw):
        out = np.asarray(generate_from_params(
            params, np.asarray(prompt)[None], cfg, max_new_tokens=n,
            **kw)._data)
        return out[0, len(prompt):].tolist()

    def traffic(n, seed):
        rng = np.random.default_rng(seed)
        reqs = []
        for i in range(n):
            kw = ({"do_sample": True, "temperature": 0.7 + 0.1 * i,
                   "top_p": 0.85, "seed": 11 + i} if i % 2 else {})
            reqs.append(serving.Request(rng.integers(0, 97, 5 + 2 * (i % 4)),
                                        max_new_tokens=4 + (i % 3), **kw))
        return reqs

    def golden(reqs):
        out = {}
        for r in reqs:
            kw = ({"do_sample": True, "temperature": r.temperature,
                   "top_p": r.top_p, "seed": r.seed} if r.do_sample else {})
            out[r.request_id] = ref(r.prompt, r.max_new_tokens, **kw)
        return out

    _SERVING = (serving, factory, ref, traffic, golden)
    _SERVING_PC.update(params=params, cfg=cfg)
    return _SERVING


_SERVING_PC = {}


def _mp_factory(**kw):
    """Two-arg (idx, mesh) factory over the shared fixture params — the
    topology-elastic supervisor's deployment shape (a replica = an mp
    group whose mesh changes across reforms)."""
    from paddle_tpu import serving
    _serving_fixture()
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)

    def factory(i, mesh):
        return serving.Engine(params=_SERVING_PC["params"],
                              config=_SERVING_PC["cfg"], mesh=mesh,
                              comm_backend="gspmd", **kw)

    return factory


def _check_bitwise(results, reqs, golden):
    missing = [r.request_id for r in reqs if r.request_id not in results]
    wrong = [r.request_id for r in reqs if r.request_id in results
             and results[r.request_id].tokens != golden[r.request_id]]
    return len(missing), not (missing or wrong)


def leg_serve_kill_resume(trials, n_reqs, seed):
    """Abrupt kill mid-decode; recover from the last cadence snapshot."""
    import time

    from paddle_tpu.incubate.checkpoint import CheckpointManager
    from paddle_tpu.utils import fault_injection as fi

    serving, factory, _, traffic, golden = _serving_fixture()
    dropped, bitwise, lat = 0, True, []
    for t in range(trials):
        reqs = traffic(n_reqs, seed + t)
        gold = golden(reqs)
        d = tempfile.mkdtemp(prefix="serve_chaos_")
        try:
            mgr = CheckpointManager(d, async_save=False,
                                    site="serving_snapshot")
            eng = factory().attach_checkpoint(mgr, every=2)
            results = {}
            with fi.inject(fi.FaultPlan(kill_at_decode_step=4 + t)):
                for r in reqs:
                    eng.submit(r)
                try:
                    while eng.step():
                        results.update(eng.pop_results())
                    raise AssertionError("kill did not fire")
                except fi.Preemption:
                    t_kill = time.perf_counter()
                del eng                         # the process is gone
                eng2 = factory().attach_checkpoint(mgr, every=0)
                eng2.load_state_dict(mgr.restore())
                eng2.step()                     # serving again
                lat.append(time.perf_counter() - t_kill)
                results.update(eng2.run())
            miss, ok = _check_bitwise(results, reqs, gold)
            dropped += miss
            bitwise &= ok
        finally:
            shutil.rmtree(d, ignore_errors=True)
    p99 = float(np.percentile(lat, 99)) if lat else 0.0
    return {"bitwise": bitwise, "dropped": dropped, "recovery_p99_s": p99,
            "trials": trials}


def leg_serve_rolling_restart(n_reqs, seed):
    from paddle_tpu.serving.supervisor import ServingSupervisor

    serving, factory, _, traffic, golden = _serving_fixture()
    reqs = traffic(n_reqs, seed)
    gold = golden(reqs)
    d = tempfile.mkdtemp(prefix="serve_chaos_")
    try:
        sup = ServingSupervisor(factory, num_replicas=2, snapshot_dir=d)
        for r in reqs:
            sup.submit(r)
        for _ in range(2):
            sup.step()
        sup.rolling_restart()
        results = sup.run()
        miss, ok = _check_bitwise(results, reqs, gold)
        return {"bitwise": ok, "dropped": miss,
                "alive": sup.alive_replicas}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def leg_serve_snapshot_io(seed):
    """Snapshot write chaos + on-disk rot: retry, quarantine, fall back."""
    from paddle_tpu.incubate.checkpoint import CheckpointManager, ckpt_counters
    from paddle_tpu.utils import fault_injection as fi

    serving, factory, ref, traffic, golden = _serving_fixture()
    reqs = traffic(3, seed)
    gold = golden(reqs)
    d = tempfile.mkdtemp(prefix="serve_chaos_")
    try:
        before = ckpt_counters()
        mgr = CheckpointManager(d, async_save=False, retries=2,
                                retry_backoff=0.01, site="serving_snapshot")
        eng = factory().attach_checkpoint(mgr, every=0)
        for r in reqs:
            eng.submit(r)
        with fi.inject(fi.FaultPlan(io_error_on_snapshots=[1])):
            for _ in range(3):
                eng.step()
            eng.save_snapshot()         # injected OSError -> retried
            for _ in range(2):
                eng.step()
            eng.save_snapshot()
        retries = ckpt_counters()["save_retries"] - before["save_retries"]
        newest = mgr.latest_step()
        with open(os.path.join(d, f"step_{newest}", "state.pdckpt"),
                  "r+b") as f:
            f.seek(-8, 2)
            f.write(b"\x00" * 8)        # rot the newest snapshot
        results = dict(eng.pop_results())
        eng2 = factory()
        eng2.load_state_dict(mgr.restore())   # quarantines + falls back
        quarantined = ckpt_counters()["quarantined"] - before["quarantined"]
        results.update(eng2.run())
        miss, ok = _check_bitwise(results, reqs, gold)
        return {"recovered": ok and quarantined == 1 and retries == 1,
                "dropped": miss, "retries": retries,
                "quarantined": quarantined,
                "fell_back_to": mgr.last_restored_step}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def leg_serve_stale_heartbeat(seed):
    import time

    from paddle_tpu.serving.supervisor import ServingSupervisor
    from paddle_tpu.utils import fault_injection as fi

    serving, factory, _, traffic, golden = _serving_fixture()
    reqs = traffic(4, seed)
    gold = golden(reqs)
    d = tempfile.mkdtemp(prefix="serve_chaos_")
    try:
        sup = ServingSupervisor(
            factory, num_replicas=2, snapshot_dir=os.path.join(d, "snap"),
            snapshot_every=2, heartbeat_dir=os.path.join(d, "hb"),
            heartbeat_timeout=0.05)
        with fi.inject(fi.FaultPlan(stale_heartbeat_ranks=[1])):
            for r in reqs:
                sup.submit(r)
            for _ in range(3):
                sup.step()
            time.sleep(0.1)             # replica1's heartbeat file rots
            results = sup.run()
        miss, ok = _check_bitwise(results, reqs, gold)
        return {"bitwise": ok, "dropped": miss,
                "heartbeats_dropped": fi.stats()["heartbeats_dropped"]}
    finally:
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# topology-elastic ladder (mesh-reforming supervisor + reshard-on-load)
# ---------------------------------------------------------------------------

ELASTIC_FLAGS = {"FLAGS_grad_comm": "on",
                 "FLAGS_weight_update_sharding": True}


def _elastic_fixture(seed, k=1, width=16, rows=16, steps=12):
    """(factory, batch_fn, golden_params) for one elastic leg: a dp-mesh
    TrainStep factory under weight-update sharding, a deterministic
    global-batch schedule, and the uninterrupted dp=8 golden params."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import env as dist_env

    def factory(mesh):
        paddle.set_flags(dict(DEFAULT_FLAGS))
        paddle.set_flags(ELASTIC_FLAGS)
        paddle.seed(seed)
        m = nn.Sequential(nn.Linear(width, width), nn.GELU(),
                          nn.Linear(width, 8))
        opt = paddle.optimizer.AdamW(0.01, parameters=m.parameters())
        return paddle.jit.TrainStep(m, nn.MSELoss(), opt, mesh=mesh,
                                    accumulate_steps=k)

    rng = np.random.default_rng(seed)
    X = rng.standard_normal((steps, rows, width)).astype(np.float32)
    Y = rng.standard_normal((steps, rows, 8)).astype(np.float32)
    batch_fn = lambda t: (X[t], Y[t])  # noqa: E731

    mesh = dist_env.create_hybrid_mesh(dp=8)
    g = factory(mesh)
    for i in range(steps):
        g(paddle.to_tensor(X[i]), paddle.to_tensor(Y[i]))
    golden = {n: np.asarray(a) for n, a in g.params.items()}
    dist_env.set_mesh(None)
    return factory, batch_fn, golden


def _max_dev(a, b):
    import numpy as np
    return max(float(np.abs(a[n] - np.asarray(b[n])).max()) for n in a)


def leg_elastic_kill_shrink(seed, steps=12, kill_step=5, save_every=2,
                            k=1, name="elastic-kill-shrink-resume"):
    """Kill one rank mid-run on dp=8; the supervisor re-forms dp=4 and
    resumes from the resharded snapshot. Gates: the shrink happened with
    zero manual steps, the post-shrink trajectory is BITWISE identical to
    an independent dp=4 restore of the same snapshot, and the final
    params track the uninterrupted dp=8 run within tolerance."""
    import tempfile

    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.distributed import elastic, env as dist_env
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    from paddle_tpu.utils import fault_injection as fi

    factory, batch_fn, golden = _elastic_fixture(seed, k=k, steps=steps)
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d, async_save=False, keep_last_n=50)
        sup = elastic.ElasticMeshSupervisor(
            factory, mgr, global_batch=16, save_every=save_every, grow=False)
        with fi.inject(fi.FaultPlan(chip_loss_at={kill_step: [2]})):
            step = sup.run(batch_fn, steps)
        final = {n: np.asarray(a) for n, a in step.params.items()}
        shrinks = [e for e in sup.events if e["kind"] == "shrink"]
        restored = shrinks[0]["restored_step"] if shrinks else None
        # independent dp=4 resume from the SAME snapshot: bitwise gate
        bitwise = False
        if restored is not None:
            dist_env.set_mesh(None)
            mesh4 = dist_env.create_hybrid_mesh(
                dp=4, devices=[jax.devices()[r] for r in (0, 1, 3, 4)])
            ref = factory(mesh4)
            ref.load_state_dict(mgr.restore(restored))
            for t in range(restored, steps):
                x, y = batch_fn(t)
                ref(paddle.to_tensor(x), paddle.to_tensor(y))
            bitwise = all(
                np.array_equal(final[n], np.asarray(a))
                for n, a in ref.params.items())
        dev = _max_dev(golden, final)
        out = {"name": name,
               "shrank": bool(shrinks) and shrinks[0]["dp"] == 4,
               "restored_step": restored, "bitwise_vs_dp4": bitwise,
               "max_dev_vs_dp8": dev, "tol": 2e-3,
               "events": [(e["kind"], e["dp"]) for e in sup.events],
               "counters": profiler.elastic_counters()}
        out["ok"] = out["shrank"] and bitwise and dev < out["tol"]
    dist_env.set_mesh(None)
    paddle.set_flags(dict(DEFAULT_FLAGS))
    return out


def leg_elastic_grow_back(seed, steps=12, kill_step=4, return_step=8,
                          save_every=2):
    """The lost rank returns mid-run: the supervisor grows the mesh back
    (dp=8 again, kill of rank 0 makes the shrunk mesh NON-contiguous) and
    finishes within tolerance of the uninterrupted run."""
    import tempfile

    import numpy as np
    from paddle_tpu import profiler
    import paddle_tpu as paddle
    from paddle_tpu.distributed import elastic, env as dist_env
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    from paddle_tpu.utils import fault_injection as fi

    factory, batch_fn, golden = _elastic_fixture(seed, steps=steps)
    with tempfile.TemporaryDirectory() as d:
        mgr = CheckpointManager(d, async_save=False, keep_last_n=50)
        sup = elastic.ElasticMeshSupervisor(
            factory, mgr, global_batch=16, save_every=save_every)
        with fi.inject(fi.FaultPlan(chip_loss_at={kill_step: [0]},
                                    chip_return_at={return_step: [0]})):
            step = sup.run(batch_fn, steps)
        final = {n: np.asarray(a) for n, a in step.params.items()}
        kinds = [e["kind"] for e in sup.events]
        dev = _max_dev(golden, final)
        out = {"name": "elastic-grow-back",
               "shrank": "shrink" in kinds, "grew": "grow" in kinds,
               "final_dp": sup.dp, "max_dev_vs_dp8": dev, "tol": 2e-3,
               "events": [(e["kind"], e["dp"]) for e in sup.events],
               "counters": profiler.elastic_counters()}
        out["ok"] = (out["shrank"] and out["grew"] and sup.dp == 8
                     and dev < out["tol"])
    dist_env.set_mesh(None)
    paddle.set_flags(dict(DEFAULT_FLAGS))
    return out


def leg_elastic_shrink_accum(seed, steps=12, kill_step=5, save_every=3):
    """accumulate_steps=2 with the snapshot cadence landing MID
    accumulation window: the resharded accumulator + micro counter must
    continue the window consistently on the shrunk mesh."""
    out = leg_elastic_kill_shrink(seed, steps=steps, kill_step=kill_step,
                                  save_every=save_every, k=2,
                                  name="elastic-shrink-accum")
    out["name"] = "elastic-shrink-accum"
    # save_every=3 with k=2: snapshots at micro 3 and 9 are mid-window
    out["mid_window_restore"] = out["restored_step"] is not None and \
        out["restored_step"] % 2 == 1
    out["ok"] = out["ok"] and out["mid_window_restore"]
    return out


def run_elastic_ladder(deterministic=False, seed=7):
    """The topology-elastic chaos ladder. ``deterministic=True`` is the
    fast tier-1 sub-rung (kill-shrink-resume + grow-back at small step
    counts); the full ladder adds the mid-accumulation-window shrink and
    prints machine-greppable lines. Every leg is injected chip loss —
    zero wall-clock dependence."""
    from paddle_tpu import profiler

    profiler.reset_elastic_counters()
    if deterministic:
        ks = leg_elastic_kill_shrink(seed, steps=8, kill_step=4)
        gb = leg_elastic_grow_back(seed + 1, steps=8, kill_step=3,
                                   return_step=6)
        return {"kill_shrink": ks, "grow_back": gb,
                "ok": ks["ok"] and gb["ok"],
                "elastic": profiler.elastic_counters()}
    ks = leg_elastic_kill_shrink(seed)
    print(f"FAULT_SMOKE elastic-kill-shrink-resume: "
          f"{'OK' if ks['ok'] else 'FAIL'}  dp8->dp4 "
          f"restored=step_{ks['restored_step']} "
          f"bitwise-vs-independent-dp4={ks['bitwise_vs_dp4']} "
          f"max-dev-vs-dp8={ks['max_dev_vs_dp8']:.2e}")
    gb = leg_elastic_grow_back(seed + 1)
    print(f"FAULT_SMOKE elastic-grow-back: "
          f"{'OK' if gb['ok'] else 'FAIL'}  events={gb['events']} "
          f"final-dp={gb['final_dp']} "
          f"max-dev-vs-dp8={gb['max_dev_vs_dp8']:.2e}")
    sa = leg_elastic_shrink_accum(seed + 2)
    print(f"FAULT_SMOKE elastic-shrink-accum: "
          f"{'OK' if sa['ok'] else 'FAIL'}  "
          f"mid-window-restore={sa['mid_window_restore']} "
          f"bitwise-vs-independent-dp4={sa['bitwise_vs_dp4']} "
          f"max-dev-vs-dp8={sa['max_dev_vs_dp8']:.2e}")
    out = {"kill_shrink": ks, "grow_back": gb, "shrink_accum": sa,
           "ok": ks["ok"] and gb["ok"] and sa["ok"],
           "elastic": profiler.elastic_counters()}
    print(f"FAULT_SMOKE elastic-ladder: {'OK' if out['ok'] else 'FAIL'}  "
          f"{profiler.elastic_summary()}")
    return out


def leg_serve_chip_kill_reform(trials, n_reqs, seed):
    """One chip of an mp=2 group dies mid-traffic: the supervisor marks
    the whole group down deterministically, re-forms it over the
    surviving chip through the MP-PORTABLE snapshot path and completes
    every request bitwise with zero drops. Recovery latency is the
    elastic ledger's measured reform wall time."""
    import jax as _jax

    from paddle_tpu import profiler
    from paddle_tpu.serving.supervisor import ServingSupervisor
    from paddle_tpu.utils import fault_injection as fi

    serving, _, _, traffic, golden = _serving_fixture()
    factory = _mp_factory()
    dropped, bitwise, degraded_ok, lat = 0, True, True, []
    for t in range(trials):
        reqs = traffic(n_reqs, seed + t)
        gold = golden(reqs)
        d = tempfile.mkdtemp(prefix="serve_elastic_")
        try:
            with fi.inject(fi.FaultPlan(
                    serving_chip_loss_at={3 + t: (1,)})):
                sup = ServingSupervisor(factory, num_replicas=2, mp=2,
                                        devices=_jax.devices()[:4],
                                        snapshot_dir=d, snapshot_every=2)
                results = sup.run(reqs)
                degraded_ok &= sup.telemetry()["replica0"]["mp"] == 1
                sup.shutdown()
            lat.append(
                profiler.elastic_counters()["reform_latency_s_last"])
            miss, ok = _check_bitwise(results, reqs, gold)
            dropped += miss
            bitwise &= ok
        finally:
            shutil.rmtree(d, ignore_errors=True)
    p99 = float(np.percentile(lat, 99)) if lat else 0.0
    return {"bitwise": bitwise and degraded_ok, "dropped": dropped,
            "recovery_p99_s": p99, "trials": trials}


def leg_serve_degraded_shed_grow_back(seed, n_reqs=16):
    """Degraded-capacity operation end to end: a chip loss halves group
    0, the sustained backlog sheds lowest-class work with live
    retry_after hints, the chip returns and the group grows back with
    ZERO new traces (memoized builders); every non-shed request
    completes bitwise, zero drops."""
    import jax as _jax

    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.serving.supervisor import ServingSupervisor
    from paddle_tpu.utils import fault_injection as fi

    serving, _, ref, _, _ = _serving_fixture()
    _shed_keys = ("FLAGS_serving_shed_high", "FLAGS_serving_shed_low",
                  "FLAGS_serving_shed_window")
    _saved = {k: paddle.get_flags()[k] for k in _shed_keys}
    paddle.set_flags({"FLAGS_serving_shed_high": 0.3,
                      "FLAGS_serving_shed_low": 0.1,
                      "FLAGS_serving_shed_window": 2})
    factory = _mp_factory(max_queue=12, shed=True)
    rng = np.random.default_rng(seed)
    reqs = [serving.Request(rng.integers(0, 97, 5 + (i % 3)),
                            max_new_tokens=6 + (i % 3),
                            priority="best_effort" if i % 2 else "batch")
            for i in range(n_reqs)]
    d = tempfile.mkdtemp(prefix="serve_elastic_")
    try:
        # loss only — NO scheduled return: the whole run serves degraded,
        # so the traces baseline below is captured BEFORE the grow-back
        # (a return firing inside run() would grow early and make the
        # zero-retraces comparison vacuously compare post-grow to itself)
        with fi.inject(fi.FaultPlan(serving_chip_loss_at={2: (1,)})):
            sup = ServingSupervisor(factory, num_replicas=2, mp=2,
                                    devices=_jax.devices()[:4],
                                    snapshot_dir=d, snapshot_every=2)
            results = sup.run(reqs)
            degraded = sup.telemetry()["replica0"]["mp"] == 1
        # plan deactivated = the chip came back: grow in the guard loop
        traces = profiler.serving_counters()["paged_traces"]
        guard = 0
        while sup.telemetry()["replica0"]["mp"] != 2 and guard < 64:
            sup.step()
            guard += 1
        grown = degraded and sup.telemetry()["replica0"]["mp"] == 2
        no_retrace = \
            profiler.serving_counters()["paged_traces"] == traces
        sup.shutdown()
        miss = [r for r in reqs if r.request_id not in results]
        shed = [r for r in reqs if r.request_id in results
                and results[r.request_id].finish_reason == "shed"]
        done = [r for r in reqs if r.request_id in results
                and results[r.request_id].finish_reason
                in ("stop", "length")]
        bitwise = all(results[r.request_id].tokens
                      == ref(r.prompt, r.max_new_tokens) for r in done)
        hints = all(results[r.request_id].retry_after is not None
                    for r in shed)
        return {"ok": (bitwise and hints and grown and no_retrace
                       and not miss and len(shed) > 0),
                "dropped": len(miss), "shed": len(shed),
                "completed": len(done), "bitwise": bitwise,
                "retry_hints": hints, "grew_back": grown,
                "zero_retraces": no_retrace}
    finally:
        paddle.set_flags(_saved)
        shutil.rmtree(d, ignore_errors=True)


def run_serving_elastic_ladder(deterministic=False, seed=7):
    """The topology-elastic SERVING ladder (chip-loss reform of mp groups
    on mp-portable snapshots). ``deterministic=True`` is the fast tier-1
    sub-rung: one chip-kill-reform trial + the degraded-shed-grow-back
    leg at tiny traffic. The full ladder runs several kill trials and
    reports the reform recovery-latency p99. Every leg is injected chip
    loss — zero wall-clock dependence; requests_dropped must be 0."""
    from paddle_tpu import profiler

    profiler.reset_serving_counters()
    if deterministic:
        ck = leg_serve_chip_kill_reform(trials=1, n_reqs=4, seed=seed)
        gb = leg_serve_degraded_shed_grow_back(seed + 40, n_reqs=10)
        dropped = ck["dropped"] + gb["dropped"]
        return {"chip_kill_reform": ck, "shed_grow_back": gb,
                "requests_dropped": dropped,
                "ok": ck["bitwise"] and gb["ok"] and dropped == 0,
                "elastic": profiler.elastic_counters()}
    ck = leg_serve_chip_kill_reform(trials=3, n_reqs=6, seed=seed)
    print(f"FAULT_SMOKE serve-chip-kill-reform: "
          f"{'OK' if ck['bitwise'] and not ck['dropped'] else 'FAIL'}  "
          f"trials={ck['trials']} dropped={ck['dropped']} "
          f"reform-p99={ck['recovery_p99_s'] * 1e3:.0f}ms "
          f"bitwise-equal-degraded")
    gb = leg_serve_degraded_shed_grow_back(seed + 40, n_reqs=16)
    print(f"FAULT_SMOKE serve-degraded-shed-grow-back: "
          f"{'OK' if gb['ok'] else 'FAIL'}  shed={gb['shed']} "
          f"completed={gb['completed']} dropped={gb['dropped']} "
          f"grew-back={gb['grew_back']} zero-retraces={gb['zero_retraces']}")
    dropped = ck["dropped"] + gb["dropped"]
    out = {"chip_kill_reform": ck, "shed_grow_back": gb,
           "requests_dropped": dropped,
           "ok": ck["bitwise"] and gb["ok"] and dropped == 0,
           "elastic": profiler.elastic_counters()}
    print(f"FAULT_SMOKE serving-elastic-ladder: "
          f"{'OK' if out['ok'] else 'FAIL'}  "
          f"requests-dropped={dropped}  {profiler.elastic_summary()}")
    return out


def run_serving_ladder(quick=True, deterministic=False, seed=7):
    """The serving chaos ladder. ``deterministic=True`` is the fast tier-1
    sub-rung: kill-resume + rolling-restart only, tiny traffic, no
    wall-clock reporting. The full ladder adds snapshot-IO chaos,
    stale-heartbeat failover and p99 recovery latency over several kill
    trials. Returns a machine-readable dict; total requests_dropped must
    be 0."""
    from paddle_tpu import profiler

    profiler.reset_serving_counters()
    if deterministic:
        kr = leg_serve_kill_resume(trials=1, n_reqs=4, seed=seed)
        rr = leg_serve_rolling_restart(n_reqs=4, seed=seed + 50)
        out = {"kill_resume": kr, "rolling_restart": rr,
               "requests_dropped": kr["dropped"] + rr["dropped"]}
        out["recovery"] = profiler.recovery_counters()
        return out
    trials = 3 if quick else 5
    kr = leg_serve_kill_resume(trials=trials, n_reqs=6, seed=seed)
    print(f"FAULT_SMOKE serve-kill-resume: "
          f"{'OK' if kr['bitwise'] and not kr['dropped'] else 'FAIL'}  "
          f"trials={kr['trials']} dropped={kr['dropped']} "
          f"recovery-p99={kr['recovery_p99_s'] * 1e3:.0f}ms bitwise-equal")
    rr = leg_serve_rolling_restart(n_reqs=6, seed=seed + 50)
    print(f"FAULT_SMOKE serve-rolling-restart: "
          f"{'OK' if rr['bitwise'] and not rr['dropped'] else 'FAIL'}  "
          f"dropped={rr['dropped']} alive={rr['alive']}/2 bitwise-equal")
    io = leg_serve_snapshot_io(seed=seed + 100)
    print(f"FAULT_SMOKE serve-snapshot-io: "
          f"{'OK' if io['recovered'] and not io['dropped'] else 'FAIL'}  "
          f"retries={io['retries']} quarantined={io['quarantined']} "
          f"fell-back-to=step_{io['fell_back_to']} dropped={io['dropped']}")
    hb = leg_serve_stale_heartbeat(seed=seed + 150)
    print(f"FAULT_SMOKE serve-stale-heartbeat: "
          f"{'OK' if hb['bitwise'] and not hb['dropped'] else 'FAIL'}  "
          f"beats-suppressed={hb['heartbeats_dropped']} "
          f"dropped={hb['dropped']} bitwise-equal")
    out = {"kill_resume": kr, "rolling_restart": rr, "snapshot_io": io,
           "stale_heartbeat": hb,
           "requests_dropped": (kr["dropped"] + rr["dropped"]
                                + io["dropped"] + hb["dropped"]),
           "recovery_p99_s": kr["recovery_p99_s"]}
    out["recovery"] = profiler.recovery_counters()
    print(f"FAULT_SMOKE serving-ladder: "
          f"{'OK' if out['requests_dropped'] == 0 else 'FAIL'}  "
          f"requests-dropped={out['requests_dropped']} "
          f"recovery-p99={out['recovery_p99_s'] * 1e3:.0f}ms  "
          f"{out['recovery']}")
    return out


# ---------------------------------------------------------------------------
# silent-data-corruption (SDC) ladder — fingerprints, peer repair, shadow
# audit, wire CRC, at-rest scrub


_SDC_FLAG_DEFAULTS = {
    "FLAGS_sdc_check_every": 0,
    "FLAGS_sdc_quarantine_threshold": 2,
    "FLAGS_serving_audit_rate": 0.0,
    "FLAGS_serving_audit_threshold": 2,
    "FLAGS_kv_transfer_crc": False,
}


def _sdc_train_run(flags, plan=None, steps=6, seed=7):
    """One short dp=8 data-parallel run under ``flags`` (and an optional
    fault plan); returns (loss, params, sdc counters, anomaly counters)."""
    import contextlib

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import env as dist_env, integrity
    from paddle_tpu.jit.train_step import (anomaly_counters,
                                           reset_anomaly_counters)
    from paddle_tpu.utils import fault_injection as fi

    integrity.reset_sdc_counters()
    reset_anomaly_counters()
    dist_env.set_mesh(None)
    merged = dict(_SDC_FLAG_DEFAULTS)
    merged["FLAGS_grad_comm"] = "on"
    merged.update(flags or {})
    step = build_step(paddle, nn, seed, flags=merged,
                      mesh=dist_env.create_hybrid_mesh(dp=8))
    X, Y = make_data(steps, seed + 1)
    ctx = fi.inject(plan) if plan is not None else contextlib.nullcontext()
    with ctx:
        params, loss = run(paddle, step, X, Y)
    out = (loss, params, dict(integrity.sdc_counters()),
           dict(anomaly_counters()))
    dist_env.set_mesh(None)
    paddle.set_flags(dict(_SDC_FLAG_DEFAULTS))
    return out


def leg_sdc_train_repair(steps, seed, deterministic=False):
    """A mantissa bit flip lands on one replica's replicated params: the
    fused fingerprint catches it at the next check boundary, the majority
    vote localizes the minority replica, the peer repair rewrites its
    bytes in place and re-dispatches the SAME step — the final params are
    BITWISE the fault-free run's, with zero disk restores and zero steps
    lost. Also asserts the exactness contract (sdc-on clean == sdc-off
    clean, bitwise) and, in the full rung, that the verdict rides the
    guard's existing combined fetch (host_syncs == steps)."""
    from paddle_tpu.utils import fault_injection as fi

    g_loss, g_params, _, _ = _sdc_train_run({}, steps=steps, seed=seed)
    c_loss, c_params, c_sdc, _ = _sdc_train_run(
        {"FLAGS_sdc_check_every": 1}, steps=steps, seed=seed)
    clean_ok = (c_loss == g_loss
                and all(np.array_equal(c_params[n], g_params[n])
                        for n in g_params)
                and c_sdc["fingerprint_checks"] == steps
                and c_sdc["fingerprint_mismatches"] == 0)
    plan = fi.FaultPlan(bitflip_at={2: (3, None, 12)})
    f_loss, f_params, f_sdc, _ = _sdc_train_run(
        {"FLAGS_sdc_check_every": 1}, plan=plan, steps=steps, seed=seed)
    repaired_ok = (f_sdc["fingerprint_mismatches"] == 1
                   and f_sdc["repairs"] == 1
                   and f_sdc["repair_redispatches"] == 1
                   and f_sdc.get("repairs_rank3") == 1)
    bitwise = (f_loss == g_loss
               and all(np.array_equal(f_params[n], g_params[n])
                       for n in g_params))
    syncs_ok = True
    if not deterministic:
        _, _, _, s_an = _sdc_train_run(
            {"FLAGS_sdc_check_every": 1, "FLAGS_anomaly_policy": "skip"},
            steps=steps, seed=seed)
        syncs_ok = (s_an["steps"] == steps
                    and s_an["host_syncs"] == steps)
    return {"ok": clean_ok and repaired_ok and bitwise and syncs_ok,
            "clean_bitwise": clean_ok, "repaired": repaired_ok,
            "bitwise": bitwise, "host_syncs_flat": syncs_ok,
            "sdc": f_sdc}


def leg_sdc_train_quarantine(steps, seed):
    """A repeat offender: two flips land on the SAME rank across the run.
    Every one is repaired in place (training never rewinds), the repair
    ledger charges the rank, and once the charge crosses
    FLAGS_sdc_quarantine_threshold the quarantine policy reports the chip
    to the elastic supervisor's failure detector as LOST."""
    from paddle_tpu.distributed import integrity
    from paddle_tpu.distributed.elastic import ElasticMeshSupervisor
    from paddle_tpu.utils import fault_injection as fi

    plan = fi.FaultPlan(bitflip_at={1: (2, None, 12), 3: (2, None, 14)})
    loss, _, sdc, _ = _sdc_train_run(
        {"FLAGS_sdc_check_every": 1, "FLAGS_sdc_quarantine_threshold": 2},
        plan=plan, steps=steps, seed=seed)
    charged = sdc.get("repairs_rank2") == 2 and sdc["repairs"] == 2
    # the ledger survives the run teardown until reset: re-arm the
    # threshold flag and ask the detector what it would do about it
    import paddle_tpu as paddle
    paddle.set_flags({"FLAGS_sdc_check_every": 1,
                      "FLAGS_sdc_quarantine_threshold": 2})
    for _ in range(2):
        integrity.note_repair(2)
    sup = ElasticMeshSupervisor(lambda *a, **kw: None, None, 8,
                                quarantine=True)
    detected = 2 in sup._detect(0)
    sup_off = ElasticMeshSupervisor(lambda *a, **kw: None, None, 8)
    policy_gated = 2 not in sup_off._detect(0)
    integrity.reset_sdc_counters()
    paddle.set_flags(dict(_SDC_FLAG_DEFAULTS))
    return {"ok": charged and detected and policy_gated,
            "charged": charged, "detected": detected,
            "policy_gated": policy_gated, "loss_finite": np.isfinite(loss)}


def leg_sdc_serve_audit(seed):
    """FINITE KV-cache corruption on one replica: the all-finite anomaly
    guard is blind to it, but the sampled shadow audit replays finished
    greedy requests through the raw-params oracle, catches the token
    divergence, charges suspicion, and fails the replica over through the
    ordinary reform path — zero drops, every delivered stream bitwise."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import integrity
    from paddle_tpu.serving.supervisor import ServingSupervisor
    from paddle_tpu.utils import fault_injection as fi

    serving, factory, ref, _, _ = _serving_fixture()
    integrity.reset_sdc_counters()
    rng = np.random.default_rng(seed)
    reqs = [serving.Request(rng.integers(0, 97, 6 + (i % 3)),
                            max_new_tokens=8) for i in range(4)]
    gold = {r.request_id: ref(r.prompt, 8) for r in reqs}
    paddle.set_flags({"FLAGS_serving_audit_rate": 1.0,
                      "FLAGS_serving_audit_threshold": 1})
    try:
        sup = ServingSupervisor(factory, num_replicas=2,
                                audit_ref=(_SERVING_PC["params"],
                                           _SERVING_PC["cfg"]))
        # flip the top exponent bit of dim 0 of EVERY position's key in
        # one page of replica0's live pool: huge but FINITE numbers that
        # saturate the softmax — invisible to any isfinite sweep, fatal
        # to the owning stream's tokens (2048 bits span one position)
        flips = [(1, 0, 2048 * p + 30) for p in range(8)]
        with fi.inject(fi.FaultPlan(kv_bitflip_at={2: flips},
                                    kv_bitflip_engine_tag="replica0")):
            results = sup.run(reqs)
        sup.shutdown()
    finally:
        paddle.set_flags(dict(_SDC_FLAG_DEFAULTS))
    s = integrity.sdc_counters()
    miss = [r.request_id for r in reqs if r.request_id not in results]
    wrong = [r.request_id for r in reqs if r.request_id in results
             and list(results[r.request_id].tokens) != gold[r.request_id]]
    stats = fi.stats()
    integrity.reset_sdc_counters()
    return {"ok": (not miss and not wrong and s["audit_failures"] >= 1
                   and stats["kv_bitflips"] == 8),
            "dropped": len(miss), "wrong": len(wrong),
            "audits": s["audits"], "audit_failures": s["audit_failures"]}


def leg_sdc_kv_wire_crc(seed):
    """A KV page payload is corrupted ON THE WIRE between the prefill and
    decode workers: the CRC32 stamped at stream time refuses the seat,
    the transfer is dropped (typed, counted), the supervisor re-offers
    the RETAINED clean payloads, and the stream seats bitwise."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import integrity
    from paddle_tpu.serving import metrics as smetrics
    from paddle_tpu.serving.supervisor import ServingSupervisor
    from paddle_tpu.utils import fault_injection as fi

    serving, factory, ref, _, _ = _serving_fixture()
    integrity.reset_sdc_counters()
    before = smetrics.serving_counters()["transfer_crc_refusals"]
    rng = np.random.default_rng(seed)
    reqs = [serving.Request(rng.integers(0, 97, 13 + 4 * i),
                            max_new_tokens=4) for i in range(3)]
    gold = {r.request_id: ref(r.prompt, 4) for r in reqs}
    paddle.set_flags({"FLAGS_kv_transfer_crc": True})
    try:
        sup = ServingSupervisor(factory, num_replicas=2,
                                roles=("prefill", "decode"))
        with fi.inject(fi.FaultPlan(corrupt_kv_wire=[1])):
            results = sup.run(reqs)
        sup.shutdown()
    finally:
        paddle.set_flags(dict(_SDC_FLAG_DEFAULTS))
    s = integrity.sdc_counters()
    refused = smetrics.serving_counters()["transfer_crc_refusals"] - before
    miss = [r.request_id for r in reqs if r.request_id not in results]
    wrong = [r.request_id for r in reqs if r.request_id in results
             and list(results[r.request_id].tokens) != gold[r.request_id]]
    integrity.reset_sdc_counters()
    return {"ok": (not miss and not wrong and s["crc_refusals"] == 1
                   and s["crc_checks"] >= 1 and refused == 1),
            "dropped": len(miss), "wrong": len(wrong),
            "crc_checks": s["crc_checks"], "crc_refusals": s["crc_refusals"]}


def leg_sdc_ckpt_scrub(seed):
    """Bit rot in a RETAINED snapshot: the cadence scrub re-verifies the
    CRC manifests newest-first, quarantines the rotten step to
    ``*.corrupt``, and the fallback chain stays clean."""
    from paddle_tpu.distributed import integrity
    from paddle_tpu.incubate.checkpoint import CheckpointManager

    integrity.reset_sdc_counters()
    d = tempfile.mkdtemp(prefix="sdc_scrub_")
    try:
        mgr = CheckpointManager(d, keep_last_n=4, async_save=False)
        state = {"w": np.arange(8, dtype=np.float32),
                 "b": np.full((3,), float(seed), np.float32)}
        for s in (1, 2, 3):
            mgr.save(s, state)
        with open(os.path.join(d, "step_2", "state.pdckpt"), "r+b") as f:
            f.seek(-8, 2)
            f.write(b"\x00" * 8)            # rot the middle snapshot
        out = mgr.scrub()
        counters = integrity.sdc_counters()
        ok = (out["rot"] == [2] and out["scrubbed"] == 3
              and counters["rot_found"] == 1
              and not os.path.isdir(os.path.join(d, "step_2"))
              and os.path.isdir(os.path.join(d, "step_2.corrupt"))
              and mgr.latest_step() == 3
              and mgr.restore() is not None)
        integrity.reset_sdc_counters()
        return {"ok": ok, **out}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def run_sdc_ladder(deterministic=False, seed=7):
    """The silent-data-corruption ladder. ``deterministic=True`` is the
    fast tier-1 sub-rung: the train detect-repair leg (tiny, no host-sync
    audit run) + the at-rest scrub leg only. The full ladder adds the
    quarantine policy, the serving shadow audit and the wire-CRC refusal.
    Returns a machine-readable dict; ``ok`` must be True."""
    import paddle_tpu as paddle

    paddle.set_flags(dict(_SDC_FLAG_DEFAULTS))
    if deterministic:
        tr = leg_sdc_train_repair(steps=3, seed=seed, deterministic=True)
        sc = leg_sdc_ckpt_scrub(seed=seed + 10)
        return {"ok": tr["ok"] and sc["ok"], "train_repair": tr,
                "ckpt_scrub": sc}
    tr = leg_sdc_train_repair(steps=6, seed=seed)
    print(f"FAULT_SMOKE sdc-train-bitflip-repair: "
          f"{'OK' if tr['ok'] else 'FAIL'}  "
          f"mismatches={tr['sdc']['fingerprint_mismatches']} "
          f"repairs={tr['sdc']['repairs']} "
          f"redispatches={tr['sdc']['repair_redispatches']} "
          f"bitwise-equal host-syncs-flat={tr['host_syncs_flat']}")
    qa = leg_sdc_train_quarantine(steps=6, seed=seed + 20)
    print(f"FAULT_SMOKE sdc-train-quarantine: "
          f"{'OK' if qa['ok'] else 'FAIL'}  "
          f"charged={qa['charged']} detected-as-lost={qa['detected']} "
          f"policy-gated={qa['policy_gated']}")
    au = leg_sdc_serve_audit(seed=seed + 40)
    print(f"FAULT_SMOKE sdc-serve-audit-catch: "
          f"{'OK' if au['ok'] else 'FAIL'}  "
          f"audits={au['audits']} failures={au['audit_failures']} "
          f"dropped={au['dropped']} wrong={au['wrong']} bitwise-equal")
    wc = leg_sdc_kv_wire_crc(seed=seed + 60)
    print(f"FAULT_SMOKE sdc-kv-wire-crc: "
          f"{'OK' if wc['ok'] else 'FAIL'}  "
          f"checked={wc['crc_checks']} refused={wc['crc_refusals']} "
          f"dropped={wc['dropped']} wrong={wc['wrong']} bitwise-equal")
    sc = leg_sdc_ckpt_scrub(seed=seed + 80)
    print(f"FAULT_SMOKE sdc-ckpt-scrub: "
          f"{'OK' if sc['ok'] else 'FAIL'}  "
          f"scrubbed={sc['scrubbed']} rot={sc['rot']}")
    out = {"ok": all(x["ok"] for x in (tr, qa, au, wc, sc)),
           "train_repair": tr, "quarantine": qa, "serve_audit": au,
           "kv_wire_crc": wc, "ckpt_scrub": sc}
    print(f"FAULT_SMOKE sdc-ladder: {'OK' if out['ok'] else 'FAIL'}")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--kill-step", type=int, default=0,
                    help="fixed kill point (default: seeded random)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--skip-serving", action="store_true",
                    help="skip the serving chaos ladder")
    ap.add_argument("--skip-elastic", action="store_true",
                    help="skip the topology-elastic ladder")
    ap.add_argument("--skip-sdc", action="store_true",
                    help="skip the silent-data-corruption ladder")
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.utils import fault_injection as fi

    leg_kill_resume(paddle, nn, fi, args)
    leg_kill_resume(
        paddle, nn, fi, args,
        flags={"FLAGS_grad_comm": "on", "FLAGS_weight_update_sharding": True},
        mesh_fn=lambda: dist_env.create_hybrid_mesh(dp=8), k=2,
        name="kill-resume-wus")
    dist_env.set_mesh(None)
    leg_nan_skip(paddle, nn, fi, args)
    leg_nan_rollback(paddle, nn, fi, args)
    leg_io_chaos(paddle, fi, args)
    paddle.set_flags(dict(DEFAULT_FLAGS))
    if not args.skip_elastic:
        out = run_elastic_ladder(seed=args.seed)
        assert out["ok"], out
    if not args.skip_serving:
        out = run_serving_ladder(quick=False, seed=args.seed)
        assert out["requests_dropped"] == 0, out
        out = run_serving_elastic_ladder(seed=args.seed)
        assert out["ok"], out
    if not args.skip_sdc:
        out = run_sdc_ladder(seed=args.seed)
        assert out["ok"], out
    print("FAULT_SMOKE all: OK")


if __name__ == "__main__":
    main()
