#!/usr/bin/env python
"""Fault-tolerance chaos bench: kill-and-resume + anomaly-guard smoke on
CPU (JAX_PLATFORMS=cpu), exercising the whole recovery stack end to end.

Legs (each seeded, deterministic):

  1. kill-resume     — train an MLP T steps (golden), rerun with a simulated
                       preemption at a pseudo-random step, resume from the
                       latest hardened checkpoint, assert the final params
                       are BITWISE equal to the uninterrupted run
  2. kill-resume-wus — same under FLAGS_weight_update_sharding + dp=8 mesh
                       + accumulate_steps=2 (packed dp-sharded slots)
  3. nan-skip        — poison one batch mid-run under
                       FLAGS_anomaly_policy=skip; assert the step was
                       skipped compiled-side (no host sync added) and the
                       final params are finite
  4. nan-rollback    — K consecutive poisoned batches under rollback;
                       assert the step restored the last checkpoint and
                       training finished finite
  5. io-chaos        — inject transient OSErrors into checkpoint writes and
                       corrupt the latest checkpoint on disk; assert saves
                       retried and restore quarantined + fell back

  python tools_fault_smoke.py [--steps N] [--kill-step K] [--seed S]

Prints, machine-greppable:

  FAULT_SMOKE <leg>: <status>  <details>
"""
from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


DEFAULT_FLAGS = {
    "FLAGS_anomaly_policy": "off",
    "FLAGS_anomaly_max_bad_steps": 3,
    "FLAGS_grad_comm": "auto",
    "FLAGS_weight_update_sharding": False,
    "FLAGS_allreduce_dtype": "float32",
}


def build_step(paddle, nn, seed, flags=None, mesh=None, k=1):
    paddle.set_flags(dict(DEFAULT_FLAGS))
    if flags:
        paddle.set_flags(flags)
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(32, 64), nn.GELU(), nn.Dropout(0.1),
                      nn.Linear(64, 8))
    opt = paddle.optimizer.AdamW(0.01, parameters=m.parameters())
    return paddle.jit.TrainStep(m, nn.MSELoss(), opt, mesh=mesh,
                                accumulate_steps=k)


def make_data(steps, seed):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((steps, 16, 32)).astype(np.float32),
            rng.standard_normal((steps, 16, 8)).astype(np.float32))


def run(paddle, step, X, Y, lo=0, hi=None):
    hi = len(X) if hi is None else hi
    loss = None
    for i in range(lo, hi):
        loss = step(paddle.to_tensor(X[i]), paddle.to_tensor(Y[i]))
    return ({n: np.asarray(a) for n, a in step.params.items()},
            float(np.asarray(loss.numpy())) if loss is not None else None)


def leg_kill_resume(paddle, nn, fi, args, flags=None, mesh_fn=None, k=1,
                    name="kill-resume"):
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    X, Y = make_data(args.steps, args.seed)
    mesh = mesh_fn() if mesh_fn else None
    golden, gloss = run(paddle, build_step(paddle, nn, args.seed, flags,
                                           mesh, k), X, Y)

    # pseudo-random but seeded kill point, at least one checkpoint before it
    kill = args.kill_step or (3 + int(
        np.random.default_rng(args.seed).integers(args.steps - 4)))
    ckpt_dir = tempfile.mkdtemp(prefix="fault_smoke_")
    try:
        mesh = mesh_fn() if mesh_fn else None
        step_a = build_step(paddle, nn, args.seed, flags, mesh, k)
        mgr = CheckpointManager(ckpt_dir, async_save=False)
        step_a.attach_checkpoint(mgr, save_every=2)
        try:
            with fi.inject(fi.FaultPlan(preempt_at_step=kill)):
                run(paddle, step_a, X, Y)
            raise AssertionError("preemption did not fire")
        except fi.Preemption:
            pass
        del step_a

        mesh = mesh_fn() if mesh_fn else None
        step_b = build_step(paddle, nn, args.seed + 99, flags, mesh, k)
        step_b.load_state_dict(mgr.restore())
        resumed, rloss = run(paddle, step_b, X, Y, lo=step_b._step)
        for n in golden:
            np.testing.assert_array_equal(golden[n], resumed[n])
        assert rloss == gloss, (rloss, gloss)  # final loss bitwise too
        print(f"FAULT_SMOKE {name}: OK  killed@{kill} "
              f"resumed@{mgr.latest_step()} steps={args.steps} "
              f"final-loss={rloss:.6f} (golden {gloss:.6f}) bitwise-equal")
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def leg_nan_skip(paddle, nn, fi, args):
    from paddle_tpu.jit.train_step import (anomaly_counters,
                                           reset_anomaly_counters)
    X, Y = make_data(args.steps, args.seed)
    reset_anomaly_counters()
    step = build_step(paddle, nn, args.seed,
                      {"FLAGS_anomaly_policy": "skip"})
    poison = args.steps // 2
    with fi.inject(fi.FaultPlan(nan_at_steps=[poison])):
        params, loss = run(paddle, step, X, Y)
    c = anomaly_counters()
    assert c["bad_steps"] == 1 and c["skipped_updates"] == 1, c
    assert c["host_syncs"] == c["steps"], c  # zero extra syncs
    assert all(np.isfinite(v).all() for v in params.values())
    print(f"FAULT_SMOKE nan-skip: OK  poisoned@{poison} "
          f"skipped=1 host-syncs={c['host_syncs']}/{c['steps']} "
          f"final-loss={loss:.6f}")


def leg_nan_rollback(paddle, nn, fi, args):
    from paddle_tpu.incubate.checkpoint import CheckpointManager
    from paddle_tpu.jit.train_step import (anomaly_counters,
                                           reset_anomaly_counters)
    X, Y = make_data(args.steps, args.seed)
    reset_anomaly_counters()
    step = build_step(paddle, nn, args.seed,
                      {"FLAGS_anomaly_policy": "rollback",
                       "FLAGS_anomaly_max_bad_steps": 2})
    ckpt_dir = tempfile.mkdtemp(prefix="fault_smoke_")
    try:
        mgr = CheckpointManager(ckpt_dir, async_save=False)
        step.attach_checkpoint(mgr, save_every=2)
        p = args.steps // 2
        with fi.inject(fi.FaultPlan(nan_at_steps=[p, p + 1])):
            params, loss = run(paddle, step, X, Y)
        c = anomaly_counters()
        assert c["rollbacks"] == 1, c
        assert all(np.isfinite(v).all() for v in params.values())
        print(f"FAULT_SMOKE nan-rollback: OK  poisoned@{p},{p + 1} "
              f"rollbacks=1 final-loss={loss:.6f}")
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def leg_io_chaos(paddle, fi, args):
    from paddle_tpu.incubate.checkpoint import (CheckpointManager,
                                                ckpt_counters)
    ckpt_dir = tempfile.mkdtemp(prefix="fault_smoke_")
    try:
        before = ckpt_counters()
        mgr = CheckpointManager(ckpt_dir, async_save=False, retries=3,
                                retry_backoff=0.01)
        with fi.inject(fi.FaultPlan(io_error_on_writes=[1, 3])):
            mgr.save(1, {"w": np.arange(16.0), "step": 1})
            mgr.save(2, {"w": np.full(16, 2.0), "step": 2})
        retries = ckpt_counters()["save_retries"] - before["save_retries"]
        # rot the newest step on disk
        with open(os.path.join(ckpt_dir, "step_2", "state.pdckpt"),
                  "r+b") as f:
            f.seek(-8, 2)
            f.write(b"\x00" * 8)
        got = mgr.restore()
        assert int(got["step"]) == 1, got
        quarantined = (ckpt_counters()["quarantined"]
                       - before["quarantined"])
        assert quarantined == 1
        print(f"FAULT_SMOKE io-chaos: OK  transient-errors=2 "
              f"retries={retries} corrupt-quarantined={quarantined} "
              f"fell-back-to=step_1")
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--kill-step", type=int, default=0,
                    help="fixed kill point (default: seeded random)")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.utils import fault_injection as fi

    leg_kill_resume(paddle, nn, fi, args)
    leg_kill_resume(
        paddle, nn, fi, args,
        flags={"FLAGS_grad_comm": "on", "FLAGS_weight_update_sharding": True},
        mesh_fn=lambda: dist_env.create_hybrid_mesh(dp=8), k=2,
        name="kill-resume-wus")
    dist_env.set_mesh(None)
    leg_nan_skip(paddle, nn, fi, args)
    leg_nan_rollback(paddle, nn, fi, args)
    leg_io_chaos(paddle, fi, args)
    paddle.set_flags(dict(DEFAULT_FLAGS))
    print("FAULT_SMOKE all: OK")


if __name__ == "__main__":
    main()
