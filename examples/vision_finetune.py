#!/usr/bin/env python
"""Vision classification fine-tune — hapi Model + DataLoader recipe.

    python examples/vision_finetune.py            # single device
    python examples/vision_finetune.py --process-workers
                                                  # GIL-free transforms

Covers: ResNet (channels-last on TPU), transforms, DataLoader (thread or
process workers), hapi Model.fit/evaluate, amp O2, checkpoint save.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


class SyntheticImages:
    """Stand-in for an image-folder dataset (zero-egress environment)."""

    def __init__(self, n=128, size=32, classes=10, transform=None,
                 channels_last=False):
        rng = np.random.RandomState(0)
        self.x = rng.rand(n, size, size, 3).astype(np.float32)
        self.y = rng.randint(0, classes, (n, 1)).astype(np.int64)
        self.transform = transform
        self.channels_last = channels_last

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        img = self.x[i]
        if self.transform is not None:
            img = self.transform(img)
        if not self.channels_last:
            img = img.transpose(2, 0, 1)
        return img, self.y[i]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-workers", action="store_true")
    ap.add_argument("--epochs", type=int, default=1)
    args = ap.parse_args()

    import jax
    # honor a cpu request via config (the env var alone is not reliable
    # when the TPU plugin is installed — see .claude/skills/verify/SKILL.md)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import paddle_tpu as paddle
    from paddle_tpu.vision import transforms as T

    on_tpu = jax.default_backend() == "tpu"
    transform = T.Compose([T.Normalize(mean=[0.5, 0.5, 0.5],
                                       std=[0.5, 0.5, 0.5],
                                       data_format="HWC")])
    # channels-last end to end on TPU: dataset layout matches the MXU conv
    # layout, no transposes anywhere
    train = SyntheticImages(n=64, transform=transform, channels_last=on_tpu)
    val = SyntheticImages(n=32, transform=transform, channels_last=on_tpu)

    model = paddle.vision.models.resnet18(
        num_classes=10, data_format="NHWC" if on_tpu else "NCHW")
    opt = paddle.optimizer.Momentum(0.01, parameters=model.parameters())

    m = paddle.Model(model)
    m.prepare(optimizer=opt, loss=paddle.nn.CrossEntropyLoss(),
              metrics=paddle.metric.Accuracy(),
              **({"amp_level": "O2", "amp_dtype": "bfloat16"}
                 if on_tpu else {}))
    loader_kw = dict(batch_size=16, num_workers=2)
    if args.process_workers:
        loader_kw["worker_mode"] = "process"
    train_loader = paddle.io.DataLoader(train, shuffle=True, **loader_kw)
    val_loader = paddle.io.DataLoader(val, **loader_kw)

    m.fit(train_loader, val_loader, epochs=args.epochs, verbose=1)
    res = m.evaluate(val_loader, verbose=0)
    print("eval:", res)
    m.save("/tmp/vision_ckpt/final")
    print("saved /tmp/vision_ckpt/final.pdparams")


if __name__ == "__main__":
    main()
