#!/usr/bin/env python
"""BERT MLM pretraining with the fused head+CE loss — the memory recipe.

    python examples/bert_pretrain_fused.py            # real chip or CPU
    python examples/bert_pretrain_fused.py --offload  # moments in host RAM

Covers: BertForPretraining.pretraining_loss (the ``[B, S, 30k]`` logits
buffer never exists — see ops/fused_ce.py), jit.TrainStep over a
forward-computes-loss adapter, and optimizer-state host offload
(``pinned_host`` moments, streamed per step on TPU).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--offload", action="store_true",
                    help="optimizer moments live in pinned host memory")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    # a small config so the example runs anywhere; swap for BertConfig()
    # (BERT-base) on a real chip
    cfg = BertConfig(vocab_size=8192, hidden_size=256, num_hidden_layers=4,
                     num_attention_heads=8, intermediate_size=1024,
                     hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    paddle.seed(0)
    net = BertForPretraining(cfg)
    opt = paddle.optimizer.AdamW(1e-4,
                                 grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    if args.offload:
        opt._offload_opt_states = True

    class FusedPretrain(paddle.nn.Layer):
        """Adapter: forward computes the fused loss directly, so TrainStep
        never sees (or allocates) MLM logits."""

        def __init__(self, inner):
            super().__init__()
            self.inner = inner

        def forward(self, ids, labels):
            return self.inner.pretraining_loss(ids, labels)

    step = paddle.jit.TrainStep(FusedPretrain(net), lambda out: out, opt)

    rng = np.random.RandomState(0)
    for i in range(args.steps):
        ids = rng.randint(0, cfg.vocab_size, (args.batch, args.seq))
        labels = ids.copy()
        mask = rng.rand(*ids.shape) < 0.85  # keep 15% as MLM targets
        labels[mask] = -100
        ids_t = paddle.to_tensor(ids.astype(np.int64))
        lbl_t = paddle.to_tensor(labels.astype(np.int64))
        loss = step((ids_t, lbl_t), ())
        print(f"step {i}: mlm loss {float(np.asarray(loss.numpy())):.4f}",
              flush=True)

    if args.offload:
        kinds = {v.sharding.memory_kind
                 for s in step.opt_state["slots"].values()
                 for v in s.values() if getattr(v, "ndim", 0) > 0}
        print("optimizer slot memory kinds:", kinds)


if __name__ == "__main__":
    main()
