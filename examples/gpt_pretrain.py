#!/usr/bin/env python
"""GPT pretraining end-to-end — the flagship recipe.

Single chip:      python examples/gpt_pretrain.py
8-dev CPU mesh:   XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                  JAX_PLATFORMS=cpu python examples/gpt_pretrain.py --mesh

Covers: hybrid mesh, Strategy-configured Engine (amp/recompute/sharding),
checkpoint save + exact resume, and generation from the trained weights.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mesh", action="store_true",
                    help="dp2 x mp2 x sharding2 mesh (8 devices)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--ckpt", default="/tmp/gpt_ckpt/model")
    args = ap.parse_args()

    import jax
    # honor a cpu request via config (the env var alone is not reliable
    # when the TPU plugin is installed — see .claude/skills/verify/SKILL.md)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import paddle_tpu as paddle
    from paddle_tpu.distributed import Engine, Strategy, env
    from paddle_tpu.models.gpt import GPTConfig

    on_tpu = jax.default_backend() == "tpu"
    mesh = None
    if args.mesh:
        mesh = env.create_hybrid_mesh(dp=2, mp=2, pp=1, sharding=2, sp=1)

    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                    num_heads=4, max_seq_len=128,
                    compute_dtype="bfloat16" if on_tpu else "float32",
                    use_flash=on_tpu)

    strategy = Strategy({
        "recompute": {"enable": True},
        "sharding": {"enable": mesh is not None, "stage": 1,
                     "axis": "sharding"},
    })
    opt = paddle.optimizer.AdamW(
        3e-4, grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    engine = Engine(cfg, None, opt, strategy=strategy, mesh=mesh)

    rng = np.random.RandomState(0)
    def batch():
        return rng.randint(0, cfg.vocab_size, (8, 64)).astype(np.int64)

    print("training...")
    for step in range(args.steps):
        loss = float(np.asarray(jax.device_get(engine.run([batch()]))))
        if step % 5 == 0 or step == args.steps - 1:
            print(f"  step {step}: loss {loss:.4f}")

    engine.save(args.ckpt)
    print(f"checkpoint saved to {args.ckpt}.pdparams")

    # exact resume: a fresh engine restores and continues bit-identically
    import dataclasses
    engine2 = Engine(dataclasses.replace(cfg), None,
                     paddle.optimizer.AdamW(
                         3e-4,
                         grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0)),
                     strategy=strategy, mesh=mesh)
    engine2.load(args.ckpt)
    check = batch()
    ref = float(np.asarray(jax.device_get(
        engine._train_step.loss_only(check))))
    resumed = float(np.asarray(jax.device_get(
        engine2._train_step.loss_only(check))))
    assert abs(ref - resumed) < 1e-6, (ref, resumed)
    print(f"exact resume verified: loss_only {resumed:.4f} == {ref:.4f}")

    # generate from the trained weights (functional KV-cache decode)
    from paddle_tpu.models.generation import generate_from_params
    out = generate_from_params(engine._train_step.params,
                               np.array([[1, 2, 3, 4]], np.int32), cfg,
                               max_new_tokens=16, do_sample=True, top_k=5)
    print("generated token ids:", np.asarray(out.numpy())[0].tolist())


if __name__ == "__main__":
    main()
